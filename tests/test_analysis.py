"""Tests for `hhmm_tpu.analysis` — the JAX-discipline static analyzer.

Covers (ISSUE 11):

- engine mechanics: pragma suppression (same line + line above),
  allowlist parsing/scoping/required-rationale, JSON report schema,
  severity handling, rule selection;
- paired known-bad/known-good fixture snippets per NEW rule family
  (hot-path purity + raw-clock, PRNG key-reuse/dead-split, dtype
  float64/implicit, import layering) — each rule must both FIRE on its
  bad fixture and STAY SILENT on its good one;
- the legacy shim: `scripts/check_guards.py` preserves the monolith's
  exit codes and message substrings (the toy-tree regressions other
  test modules rely on), and the repo itself is clean;
- the CLI: `python -m hhmm_tpu.analysis --format json hhmm_tpu/` exits
  0 with zero unsuppressed findings (acceptance criterion);
- obs_report's `== analysis ==` section renders the JSON report;
- purity of the analyzer itself: no jax import anywhere in the
  package (it must run on jax-less hosts inside the tier-1 budget).

And (ISSUE 12):

- the concurrency family — lock-order cycles/self-deadlocks (+ the
  emitted order DAG), shared-state race guard inference (domination,
  threading.local, module containers), held-lock escape categories,
  atomic-write discipline — paired known-bad/known-good per rule;
- statement-anchored pragma suppression on multi-line statements;
- the findings ratchet (`--baseline`) CLI semantics end to end;
- `scripts/lint.py --changed` rename/delete handling in a tmp git
  repo;
- repo-clean acceptance with the concurrency family enabled: zero
  findings, ACYCLIC leaf-only lock graph, full scan under 10 s.

Everything here is pure-ast work over tmp_path toy trees + a few
subprocess runs of the thin CLIs — fast by construction (no jax
import in the analyzer process).
"""

from __future__ import annotations

import ast
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from hhmm_tpu.analysis import (  # noqa: E402
    RULES,
    AllowlistError,
    load_allowlist,
    run_analysis,
)

# ---------------------------------------------------------------------------
# helpers


def _tree(tmp_path, files):
    """Materialize {relpath: source} under tmp_path/hhmm_tpu-rooted
    toy repo; returns tmp_path."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return tmp_path


def _run(tmp_path, files, rules, paths=("hhmm_tpu",)):
    _tree(tmp_path, files)
    return run_analysis(root=tmp_path, paths=list(paths), rules=list(rules))


def _ids(report):
    return [(f.file, f.line, f.rule_id) for f in report.findings]


def _fires(report, rule_id):
    return [f for f in report.findings if f.rule_id == rule_id]


# ---------------------------------------------------------------------------
# engine


class TestEngine:
    def test_pragma_same_line_suppresses(self, tmp_path):
        rep = _run(
            tmp_path,
            {
                "hhmm_tpu/apps/x.py": (
                    "import time as _t\n\n"
                    "def f():\n"
                    "    return _t.perf_counter()  # lint: ok raw-clock -- toy\n"
                )
            },
            ["raw-clock"],
        )
        assert not rep.findings
        assert len(rep.suppressed) == 1
        assert rep.suppressed[0].rule_id == "raw-clock"

    def test_pragma_line_above_suppresses(self, tmp_path):
        rep = _run(
            tmp_path,
            {
                "hhmm_tpu/apps/x.py": (
                    "import time as _t\n\n"
                    "def f():\n"
                    "    # lint: ok raw-clock -- toy\n"
                    "    return _t.perf_counter()\n"
                )
            },
            ["raw-clock"],
        )
        assert not rep.findings and len(rep.suppressed) == 1

    def test_pragma_other_rule_does_not_suppress(self, tmp_path):
        rep = _run(
            tmp_path,
            {
                "hhmm_tpu/apps/x.py": (
                    "import time as _t\n\n"
                    "def f():\n"
                    "    return _t.perf_counter()  # lint: ok bare-except -- wrong id\n"
                )
            },
            ["raw-clock"],
        )
        assert len(_fires(rep, "raw-clock")) == 1

    def test_allowlist_file_and_line_scoping(self, tmp_path):
        files = {
            "hhmm_tpu/apps/x.py": (
                "import time as _t\n\n"
                "def f():\n"
                "    return _t.perf_counter()\n"
                "def g():\n"
                "    return _t.perf_counter()\n"
            ),
            "hhmm_tpu/analysis/allowlist.txt": (
                "raw-clock hhmm_tpu/apps/x.py:4 -- line-pinned toy entry\n"
            ),
        }
        rep = _run(tmp_path, files, ["raw-clock"])
        assert [(f.file, f.line) for f in rep.findings] == [("hhmm_tpu/apps/x.py", 6)]
        assert len(rep.suppressed) == 1
        # file-level entry suppresses both
        files["hhmm_tpu/analysis/allowlist.txt"] = (
            "raw-clock hhmm_tpu/apps/x.py -- file-level toy entry\n"
        )
        rep = _run(tmp_path, files, ["raw-clock"])
        assert not rep.findings and len(rep.suppressed) == 2

    def test_allowlist_requires_rationale(self, tmp_path):
        p = tmp_path / "allow.txt"
        p.write_text("raw-clock hhmm_tpu/apps/x.py\n")
        with pytest.raises(AllowlistError):
            load_allowlist(p)
        p.write_text("raw-clock hhmm_tpu/apps/x.py --   \n")
        with pytest.raises(AllowlistError):
            load_allowlist(p)
        p.write_text("# comment\n\nraw-clock a.py:7 -- why\n")
        entries = load_allowlist(p)
        assert len(entries) == 1 and entries[0].line == 7

    def test_unused_allowlist_entries_reported(self, tmp_path):
        files = {
            "hhmm_tpu/apps/x.py": "X = 1\n",
            "hhmm_tpu/analysis/allowlist.txt": (
                "raw-clock hhmm_tpu/apps/never.py -- stale entry\n"
            ),
        }
        rep = _run(tmp_path, files, ["raw-clock"])
        js = rep.to_json()
        assert js["allowlist_unused"] == ["raw-clock hhmm_tpu/apps/never.py"]

    def test_json_schema(self, tmp_path):
        rep = _run(tmp_path, {"hhmm_tpu/apps/x.py": "X = 1\n"}, ["raw-clock"])
        js = rep.to_json()
        for key in (
            "version",
            "root",
            "files_scanned",
            "rules",
            "findings",
            "suppressed_count",
            "allowlist_entries",
            "allowlist_unused",
            "ok",
        ):
            assert key in js
        assert js["ok"] is True
        assert js["rules"]["raw-clock"]["severity"] == "error"

    def test_warning_severity_does_not_fail(self, tmp_path):
        # a dead split is a warning: reported, but ok stays True
        rep = _run(
            tmp_path,
            {
                "hhmm_tpu/infer/x.py": (
                    "from jax import random\n\n"
                    "def f(key):\n"
                    "    k1, k2 = random.split(key)\n"
                    "    return random.normal(k1, (3,))\n"
                )
            },
            ["prng-dead-split"],
        )
        assert len(_fires(rep, "prng-dead-split")) == 1
        assert rep.findings[0].severity == "warning"
        assert rep.ok  # warnings never flip the exit code

    def test_unknown_rule_id_raises(self, tmp_path):
        with pytest.raises(KeyError):
            _run(tmp_path, {"hhmm_tpu/x.py": "X = 1\n"}, ["no-such-rule"])

    def test_syntax_error_becomes_finding(self, tmp_path):
        rep = _run(
            tmp_path,
            {"hhmm_tpu/apps/bad.py": "def broken(:\n"},
            ["raw-clock"],
        )
        assert [f.rule_id for f in rep.findings] == ["parse-error"]
        assert not rep.ok


# ---------------------------------------------------------------------------
# rule family: hot-path purity


_PURITY_BAD = """\
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def step(carry, x):
    print("tick", x)            # host IO in a scan body
    v = np.asarray(carry)       # numpy host call
    s = float(x.sum())          # cast of an array-shaped value
    i = carry.item()            # host transfer
    jax.block_until_ready(x)    # sync
    return carry, s + i + v.sum()


def run(xs):
    return lax.scan(step, 0.0, xs)
"""

_PURITY_GOOD = """\
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_K = 4


def step(carry, x):
    j = float(_K - 1)           # static constant cast: pure
    n = int(x.shape[0])         # shape read: static at trace time
    w = jnp.asarray(x, np.float32)  # np dtype attribute: pure
    return carry + j, w.sum() + n


def run(xs):
    return lax.scan(step, 0.0, xs)


def host_driver(xs):
    # host-side code may sync/print freely: not reachable from a
    # device call site
    out = jax.block_until_ready(run(xs))
    print("done")
    return np.asarray(out)
"""


class TestHotPathPurity:
    def test_bad_fixture_fires_each_op(self, tmp_path):
        rep = _run(
            tmp_path, {"hhmm_tpu/kernels/toy.py": _PURITY_BAD}, ["hot-path-purity"]
        )
        msgs = " | ".join(f.message for f in _fires(rep, "hot-path-purity"))
        for needle in (
            "print",
            "np.asarray",
            "`float(...)` cast",
            ".item()",
            "block_until_ready",
        ):
            assert needle in msgs, f"missing {needle!r} in: {msgs}"

    def test_good_fixture_silent(self, tmp_path):
        rep = _run(
            tmp_path, {"hhmm_tpu/kernels/toy.py": _PURITY_GOOD}, ["hot-path-purity"]
        )
        assert not _fires(rep, "hot-path-purity"), _ids(rep)

    def test_reachability_through_helpers_and_decorators(self, tmp_path):
        src = (
            "import jax\n"
            "from functools import partial\n\n"
            "def helper(x):\n"
            "    return x.item()\n\n"
            "@partial(jax.jit, static_argnums=0)\n"
            "def entry(n, x):\n"
            "    return helper(x) + n\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["hot-path-purity"])
        hits = _fires(rep, "hot-path-purity")
        assert len(hits) == 1 and "helper" in hits[0].message

    def test_vmap_lambda_flagged(self, tmp_path):
        src = "import jax\n\nf = jax.vmap(lambda x: float(x.sum()))\n"
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["hot-path-purity"])
        assert len(_fires(rep, "hot-path-purity")) == 1

    def test_jax_lax_chain_spelling_traced(self, tmp_path):
        # `jax.lax.scan(step, ...)` under plain `import jax` — the
        # dominant spelling in sim//kernels/ — must seed reachability
        src = (
            "import jax\n\n"
            "def step(c, x):\n"
            "    print('tick')\n"
            "    return c, x\n\n"
            "def run(xs):\n"
            "    return jax.lax.scan(step, 0.0, xs)\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["hot-path-purity"])
        hits = _fires(rep, "hot-path-purity")
        assert len(hits) == 1 and "print" in hits[0].message


class TestRawClock:
    def test_bad_fixture_fires(self, tmp_path):
        src = (
            "from time import perf_counter\n\n"
            "def drive():\n"
            "    t0 = perf_counter()\n"
            "    return perf_counter() - t0\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/apps/toy.py": src}, ["raw-clock"])
        assert len(_fires(rep, "raw-clock")) == 2

    def test_good_fixture_silent(self, tmp_path):
        # the sanctioned spelling: obs.profile.PhaseClock over one sink
        src = (
            "from hhmm_tpu.obs.profile import PhaseClock\n\n"
            "def drive(tm):\n"
            "    clock = PhaseClock(tm, round_digits=2)\n"
            "    work = 1 + 1\n"
            "    clock.mark('prep')\n"
            "    return work\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/apps/toy.py": src}, ["raw-clock"])
        assert not _fires(rep, "raw-clock")

    def test_obs_and_serve_out_of_scope(self, tmp_path):
        src = "from time import perf_counter\n\nT0 = perf_counter()\n"
        rep = _run(
            tmp_path,
            {
                "hhmm_tpu/obs/toy.py": src,  # obs IS the clock substrate
                "hhmm_tpu/serve/toy.py": src,  # serve-clock (legacy) owns it
            },
            ["raw-clock"],
        )
        assert not _fires(rep, "raw-clock")


# ---------------------------------------------------------------------------
# rule family: PRNG discipline


_PRNG_REUSE_BAD = """\
from jax import random


def draw(key):
    a = random.normal(key, (3,))
    b = random.uniform(key, (3,))    # same key: identical randomness
    return a + b
"""

_PRNG_REUSE_GOOD = """\
from jax import random


def draw(key):
    key, sub = random.split(key)
    a = random.normal(sub, (3,))
    key, sub = random.split(key)
    b = random.uniform(sub, (3,))
    return a + b


def branchy(key, flag):
    # consumptions in mutually exclusive branches never pair
    if flag:
        return random.normal(key, (3,))
    else:
        return random.uniform(key, (3,))
"""

_PRNG_LOOP_BAD = """\
from jax import random


def draws(key, n):
    out = []
    for i in range(n):
        out.append(random.normal(key, (3,)))   # same stream every iter
    return out
"""

_PRNG_LOOP_GOOD = """\
from jax import random


def draws(key, n):
    out = []
    for i in range(n):
        out.append(random.normal(random.fold_in(key, i), (3,)))
    return out


def draws_split(key, n):
    out = []
    for i in range(n):
        key, sub = random.split(key)
        out.append(random.normal(sub, (3,)))
    return out


def draws_vector(keys):
    return [random.normal(k, (3,)) for k in keys]
"""


class TestPrngKeyReuse:
    def test_reuse_fires(self, tmp_path):
        rep = _run(
            tmp_path, {"hhmm_tpu/infer/toy.py": _PRNG_REUSE_BAD}, ["prng-key-reuse"]
        )
        hits = _fires(rep, "prng-key-reuse")
        assert len(hits) == 1 and "`key`" in hits[0].message

    def test_split_between_is_silent(self, tmp_path):
        rep = _run(
            tmp_path, {"hhmm_tpu/infer/toy.py": _PRNG_REUSE_GOOD}, ["prng-key-reuse"]
        )
        assert not _fires(rep, "prng-key-reuse"), _ids(rep)

    def test_loop_reuse_fires(self, tmp_path):
        rep = _run(
            tmp_path, {"hhmm_tpu/infer/toy.py": _PRNG_LOOP_BAD}, ["prng-key-reuse"]
        )
        hits = _fires(rep, "prng-key-reuse")
        assert len(hits) == 1 and "loop" in hits[0].message

    def test_fold_in_and_per_iter_split_silent(self, tmp_path):
        rep = _run(
            tmp_path, {"hhmm_tpu/infer/toy.py": _PRNG_LOOP_GOOD}, ["prng-key-reuse"]
        )
        assert not _fires(rep, "prng-key-reuse"), _ids(rep)

    def test_attribute_chain_spelling_fires(self, tmp_path):
        # the repo's DOMINANT spelling: plain `import jax` +
        # `jax.random.*(...)` — a rule blind to it scans nothing real
        src = (
            "import jax\n\n"
            "def f(key):\n"
            "    a = jax.random.normal(key, (3,))\n"
            "    b = jax.random.uniform(key, (3,))\n"
            "    return a + b\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/infer/toy.py": src}, ["prng-key-reuse"])
        assert len(_fires(rep, "prng-key-reuse")) == 1

    def test_sequential_fold_in_derivations_silent(self, tmp_path):
        # fold_in derives, it does not exhaust: several children from
        # one parent with distinct data is the sanctioned pattern
        src = (
            "import jax\n\n"
            "def f(key):\n"
            "    k1 = jax.random.fold_in(key, 0)\n"
            "    k2 = jax.random.fold_in(key, 1)\n"
            "    return jax.random.normal(k1, (2,)) + jax.random.normal(k2, (2,))\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/infer/toy.py": src}, ["prng-key-reuse"])
        assert not _fires(rep, "prng-key-reuse"), _ids(rep)

    def test_early_return_branch_exclusive_silent(self, tmp_path):
        # `if flag: use(key); return` + later `use(key)` never both run
        src = (
            "import jax\n\n"
            "def f(key, flag):\n"
            "    if flag:\n"
            "        return jax.random.dirichlet(key, jax.numpy.ones(3))\n"
            "    return jax.random.normal(key, (3,))\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/models/toy.py": src}, ["prng-key-reuse"])
        assert not _fires(rep, "prng-key-reuse"), _ids(rep)

    def test_for_iter_split_is_not_in_loop(self, tmp_path):
        # `for sk in split(key, 2):` evaluates the iter ONCE — not a
        # per-iteration consumption of `key`
        src = (
            "import jax\n\n"
            "def f(key):\n"
            "    out = []\n"
            "    for sk in jax.random.split(key, 2):\n"
            "        kp, ka = jax.random.split(sk)\n"
            "        out.append(jax.random.normal(kp, (2,)) + jax.random.uniform(ka, (2,)))\n"
            "    return out\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/models/toy.py": src}, ["prng-key-reuse"])
        assert not _fires(rep, "prng-key-reuse"), _ids(rep)

    def test_split_then_parent_reuse_fires(self, tmp_path):
        src = (
            "from jax import random\n\n"
            "def f(key):\n"
            "    sub = random.split(key, 2)\n"
            "    x = random.normal(key, (3,))   # parent reused after split\n"
            "    return sub, x\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/infer/toy.py": src}, ["prng-key-reuse"])
        assert len(_fires(rep, "prng-key-reuse")) == 1


class TestPrngDeadSplit:
    def test_dead_split_fires(self, tmp_path):
        src = (
            "from jax import random\n\n"
            "def f(key):\n"
            "    k1, k2 = random.split(key)\n"
            "    return random.normal(k1, (3,))\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/infer/toy.py": src}, ["prng-dead-split"])
        hits = _fires(rep, "prng-dead-split")
        assert len(hits) == 1 and "`k2`" in hits[0].message

    def test_consumed_and_underscore_silent(self, tmp_path):
        src = (
            "from jax import random\n\n"
            "def f(key):\n"
            "    k1, k2 = random.split(key)\n"
            "    return random.normal(k1, (3,)) + random.uniform(k2, (3,))\n\n"
            "def g(key):\n"
            "    k1, _unused = random.split(key)\n"
            "    return random.normal(k1, (3,))\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/infer/toy.py": src}, ["prng-dead-split"])
        assert not _fires(rep, "prng-dead-split"), _ids(rep)


# ---------------------------------------------------------------------------
# rule family: dtype discipline


class TestDtype:
    def test_float64_fires_in_scope(self, tmp_path):
        src = (
            "import jax.numpy as jnp\n\n"
            "def f(x):\n"
            "    return jnp.asarray(x, jnp.float64)\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["dtype-float64"])
        assert len(_fires(rep, "dtype-float64")) == 1

    def test_string_float64_fires(self, tmp_path):
        src = "import jax.numpy as jnp\n\nZ = jnp.zeros((3,), 'float64')\n"
        rep = _run(tmp_path, {"hhmm_tpu/core/toy.py": src}, ["dtype-float64"])
        assert len(_fires(rep, "dtype-float64")) == 1

    def test_float64_out_of_scope_silent(self, tmp_path):
        src = "import numpy as np\n\ndef f(x):\n    return np.asarray(x, np.float64)\n"
        rep = _run(tmp_path, {"hhmm_tpu/models/toy.py": src}, ["dtype-float64"])
        assert not _fires(rep, "dtype-float64")

    def test_implicit_ctor_fires(self, tmp_path):
        src = "import jax.numpy as jnp\n\nZ = jnp.zeros((3,))\nO = jnp.ones(4)\n"
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["dtype-implicit"])
        assert len(_fires(rep, "dtype-implicit")) == 2

    def test_explicit_dtype_silent_both_spellings(self, tmp_path):
        src = (
            "import jax.numpy as jnp\n\n"
            "def f(x):\n"
            "    a = jnp.zeros((3,), x.dtype)      # positional\n"
            "    b = jnp.ones((3,), dtype=x.dtype)  # kwarg\n"
            "    return a + b\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["dtype-implicit"])
        assert not _fires(rep, "dtype-implicit"), _ids(rep)

    def test_bare_imported_ctor_fires(self, tmp_path):
        src = "from jax.numpy import zeros\n\nZ = zeros((3,))\n"
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["dtype-implicit"])
        assert len(_fires(rep, "dtype-implicit")) == 1


# ---------------------------------------------------------------------------
# rule family: import layering


class TestLayering:
    def test_back_edge_fires(self, tmp_path):
        src = "from hhmm_tpu.serve.online import StreamState\n\nX = 1\n"
        rep = _run(tmp_path, {"hhmm_tpu/core/toy.py": src}, ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "back-edge" in hits[0].message

    def test_lazy_back_edge_fires_too(self, tmp_path):
        src = (
            "def f():\n"
            "    from hhmm_tpu.apps.tayal import wf\n"
            "    return wf\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["layer-import"])
        assert len(_fires(rep, "layer-import")) == 1

    def test_downward_and_root_imports_silent(self, tmp_path):
        src = (
            "import hhmm_tpu\n"
            "from hhmm_tpu.core.lmath import safe_logsumexp\n"
            "from hhmm_tpu.kernels import dispatch\n"
            "from hhmm_tpu.obs.trace import span\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["layer-import"])
        assert not _fires(rep, "layer-import"), _ids(rep)

    def test_same_rank_sibling_fires(self, tmp_path):
        src = "from hhmm_tpu.batch import fit_batched\n"
        rep = _run(tmp_path, {"hhmm_tpu/models/toy.py": src}, ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "same-rank sibling" in hits[0].message

    def test_unmapped_subpackage_fires(self, tmp_path):
        src = "from hhmm_tpu.mystery import thing\n"
        rep = _run(tmp_path, {"hhmm_tpu/apps/toy.py": src}, ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "unmapped" in hits[0].message

    def test_duration_kernel_rank_pair(self, tmp_path):
        """The HSMM expansion module (`kernels/duration.py`) lives at
        kernel rank: importing core's guarded lmath is a down-edge
        (good fixture, silent); importing the model zoo that CONSUMES
        the expansion is a back-edge (bad fixture, fires) — the
        expansion must stay model-agnostic."""
        good = (
            "from hhmm_tpu.core.lmath import MASK_NEG, safe_logsumexp\n\n"
            "def expand(a):\n"
            "    return a + MASK_NEG\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy_duration.py": good},
                   ["layer-import"])
        assert not _fires(rep, "layer-import"), _ids(rep)
        bad = "from hhmm_tpu.models.hsmm import GaussianHSMM\n"
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy_duration.py": bad},
                   ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "back-edge" in hits[0].message

    def test_events_serve_rank_pair(self, tmp_path):
        """The regime-event feed (`serve/events.py`) lives at serve
        rank: consuming obs metrics and kernels' collapse is downward
        (good fixture, silent); reaching UP into the adaptation or
        maintenance planes that subscribe to it fires — subscribers
        poll the feed, the feed must not know them."""
        good = (
            "from hhmm_tpu.obs import metrics as m\n"
            "from hhmm_tpu.kernels.duration import collapse_probs\n\n"
            "def observe(p):\n"
            "    return collapse_probs(p, 1)\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy_events.py": good},
                   ["layer-import"])
        assert not _fires(rep, "layer-import"), _ids(rep)
        bad = "from hhmm_tpu.adapt import anything\n"
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy_events.py": bad},
                   ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "back-edge" in hits[0].message

    def test_pipeline_to_serve_back_edge_fires(self, tmp_path):
        """The PR 18 contract: ``pipeline`` ranks BELOW ``serve`` —
        a pipeline module importing the serving layer is a back-edge
        (flights must carry opaque groups; commits live in serve)."""
        src = "from hhmm_tpu.serve.scheduler import MicroBatchScheduler\n"
        rep = _run(tmp_path, {"hhmm_tpu/pipeline/toy.py": src}, ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "back-edge" in hits[0].message

    def test_serve_to_pipeline_import_silent(self, tmp_path):
        src = (
            "from hhmm_tpu.pipeline import InFlightTable\n"
            "from hhmm_tpu.pipeline.place import DevicePlacement\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["layer-import"])
        assert not _fires(rep, "layer-import"), _ids(rep)

    def test_pipeline_sibling_and_plan_imports(self, tmp_path):
        # pipeline shares rank 4 with models/batch (sibling: fires)
        # and sits above plan/obs (downward: silent)
        bad = "from hhmm_tpu.models import TayalHHMM\n"
        rep = _run(
            tmp_path / "bad", {"hhmm_tpu/pipeline/toy.py": bad}, ["layer-import"]
        )
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "same-rank sibling" in hits[0].message
        good = (
            "from hhmm_tpu.plan import make_plan\n"
            "from hhmm_tpu.obs import manifest\n"
        )
        rep = _run(
            tmp_path / "good", {"hhmm_tpu/pipeline/ok.py": good}, ["layer-import"]
        )
        assert not _fires(rep, "layer-import"), _ids(rep)

    def test_pragma_audits_lazy_cycle_breaker(self, tmp_path):
        src = (
            "def f():\n"
            "    from hhmm_tpu.apps.tayal import wf  # lint: ok layer-import -- toy cycle breaker\n"
            "    return wf\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["layer-import"])
        assert not _fires(rep, "layer-import") and len(rep.suppressed) == 1

    def test_relative_parent_import_resolved(self, tmp_path):
        src = "from ..serve import online\n"
        rep = _run(tmp_path, {"hhmm_tpu/core/toy.py": src}, ["layer-import"])
        assert len(_fires(rep, "layer-import")) == 1

    def test_relative_alias_subpackage_import_fires(self, tmp_path):
        # `from .. import apps` — the aliases ARE the subpackages,
        # exactly like the absolute `from hhmm_tpu import apps`
        src = "from .. import apps\n"
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "back-edge" in hits[0].message

    # paired fixtures for the maint rank (serve < maint < apps): the
    # maintenance plane may consume serve/batch and below, apps may
    # orchestrate maint — and neither inversion is silent

    def test_maint_consumes_serve_and_batch_silent(self, tmp_path):
        src = (
            "from hhmm_tpu.serve import SnapshotRegistry\n"
            "from hhmm_tpu.batch import fit_batched\n"
            "from hhmm_tpu.obs import metrics\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/maint/toy.py": src}, ["layer-import"])
        assert not _fires(rep, "layer-import"), _ids(rep)

    def test_apps_orchestrates_maint_silent(self, tmp_path):
        src = "from hhmm_tpu.maint import MaintenanceLoop\n"
        rep = _run(tmp_path, {"hhmm_tpu/apps/toy.py": src}, ["layer-import"])
        assert not _fires(rep, "layer-import"), _ids(rep)

    def test_maint_importing_apps_back_edge_fires(self, tmp_path):
        src = "from hhmm_tpu.apps.tayal import wf\n"
        rep = _run(tmp_path, {"hhmm_tpu/maint/toy.py": src}, ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "back-edge" in hits[0].message

    def test_serve_importing_maint_back_edge_fires(self, tmp_path):
        src = "from hhmm_tpu.maint import promote_snapshot\n"
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "back-edge" in hits[0].message

    # paired fixtures for the adapt rank (serve 5 < adapt 6 < maint 7):
    # the adaptation plane reads serve's per-draw signal and writes
    # back through serve's adaptation surface, maint calls DOWN into
    # its escalation ladder — and neither inversion is silent

    def test_adapt_importing_maint_back_edge_fires(self, tmp_path):
        src = "from hhmm_tpu.maint import MaintenanceLoop\n"
        rep = _run(tmp_path, {"hhmm_tpu/adapt/toy.py": src}, ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "back-edge" in hits[0].message

    def test_serve_importing_adapt_back_edge_fires(self, tmp_path):
        src = "from hhmm_tpu.adapt import AdaptationLadder\n"
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["layer-import"])
        hits = _fires(rep, "layer-import")
        assert len(hits) == 1 and "back-edge" in hits[0].message

    def test_adapt_consumes_serve_and_kernels_silent(self, tmp_path):
        src = (
            "from hhmm_tpu.serve.metrics import AdaptMetrics\n"
            "from hhmm_tpu.core.lmath import safe_logsumexp\n"
            "from hhmm_tpu.kernels import dispatch\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/adapt/toy.py": src}, ["layer-import"])
        assert not _fires(rep, "layer-import"), _ids(rep)

    def test_maint_calls_down_into_adapt_silent(self, tmp_path):
        src = "from hhmm_tpu.adapt import AdaptationLadder\n"
        rep = _run(tmp_path, {"hhmm_tpu/maint/toy.py": src}, ["layer-import"])
        assert not _fires(rep, "layer-import"), _ids(rep)


# ---------------------------------------------------------------------------
# rule: pallas-import (kernels/dispatch.py is the only Pallas entry)


# paired known-bad / known-good fixtures: same consumer module, the
# only difference is whether the Pallas kernels are reached directly or
# through the sanctioned dispatch layer
_PALLAS_BAD = (
    "from hhmm_tpu.kernels.pallas_semiring import semiring_filter\n\n"
    "def decode(lp, lA, lo, m):\n"
    "    return semiring_filter(lp, lA, lo, m)\n"
)
_PALLAS_GOOD = (
    "from hhmm_tpu.kernels.dispatch import forward_filter_dispatch\n\n"
    "def decode(lp, lA, lo, m):\n"
    "    return forward_filter_dispatch(lp, lA, lo, m, time_parallel='auto')\n"
)


class TestPallasImport:
    def test_severity_is_error(self):
        assert RULES["pallas-import"].severity == "error"

    def test_known_bad_fires(self, tmp_path):
        rep = _run(
            tmp_path, {"hhmm_tpu/infer/toy.py": _PALLAS_BAD}, ["pallas-import"]
        )
        hits = _fires(rep, "pallas-import")
        assert len(hits) == 1 and "dispatch" in hits[0].message
        assert hits[0].severity == "error"

    def test_known_good_silent(self, tmp_path):
        rep = _run(
            tmp_path, {"hhmm_tpu/infer/toy.py": _PALLAS_GOOD}, ["pallas-import"]
        )
        assert not _fires(rep, "pallas-import"), _ids(rep)

    def test_all_import_spellings_fire(self, tmp_path):
        src = (
            "import hhmm_tpu.kernels.pallas_semiring\n"
            "from hhmm_tpu.kernels.pallas_forward import pallas_forward_vg\n"
            "from hhmm_tpu.kernels import pallas_ffbs\n"
            "def f():\n"
            "    from hhmm_tpu.kernels.pallas_traj import tayal_trajectory\n"
            "    return tayal_trajectory\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/apps/toy.py": src}, ["pallas-import"])
        assert len(_fires(rep, "pallas-import")) == 4

    def test_relative_import_from_sibling_package_fires(self, tmp_path):
        src = "from ..kernels.pallas_semiring import semiring_vg\n"
        rep = _run(tmp_path, {"hhmm_tpu/infer/toy.py": src}, ["pallas-import"])
        assert len(_fires(rep, "pallas-import")) == 1

    def test_inside_kernels_package_allowed(self, tmp_path):
        # dispatch.py and the shims live here: in-package imports are
        # the sanctioned wiring, not an entry-point violation
        src = "from hhmm_tpu.kernels.pallas_semiring import semiring_filter\n"
        rep = _run(
            tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["pallas-import"]
        )
        assert not _fires(rep, "pallas-import")

    def test_scripts_and_bench_scope_fires(self, tmp_path):
        # probes/benches are in the default scan set and must go
        # through dispatch like everything else
        src = "from hhmm_tpu.kernels import pallas_semiring\n"
        rep = _run(
            tmp_path,
            {"scripts/toy_probe.py": src},
            ["pallas-import"],
            paths=("scripts",),
        )
        assert len(_fires(rep, "pallas-import")) == 1

    def test_dispatch_reexport_and_non_pallas_imports_silent(self, tmp_path):
        src = (
            "from hhmm_tpu.kernels.dispatch import semiring_filter, ffbs_pallas\n"
            "from hhmm_tpu.kernels.filtering import forward_filter\n"
            "from hhmm_tpu.kernels import viterbi\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/infer/toy.py": src}, ["pallas-import"])
        assert not _fires(rep, "pallas-import"), _ids(rep)


# ---------------------------------------------------------------------------
# the repo itself + CLI + shim contract


class TestRepoClean:
    def test_api_full_default_scan_clean(self):
        rep = run_analysis(root=REPO)
        assert rep.findings == [], "\n".join(f.format() for f in rep.findings)

    def test_cli_json_on_package_exits_zero(self):
        # ISSUE 11 acceptance criterion, verbatim invocation
        proc = subprocess.run(
            [sys.executable, "-m", "hhmm_tpu.analysis", "--format", "json", "hhmm_tpu/"],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        js = json.loads(proc.stdout)
        assert js["ok"] is True and js["findings"] == []
        assert js["files_scanned"] > 80
        # every registered rule ran
        assert set(js["rules"]) == set(RULES)

    def test_cli_list_rules(self):
        proc = subprocess.run(
            [sys.executable, "-m", "hhmm_tpu.analysis", "--list-rules"],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        assert proc.returncode == 0
        for rid in RULES:
            assert rid in proc.stdout

    def test_cli_bad_allowlist_exits_two(self, tmp_path):
        bad = tmp_path / "allow.txt"
        bad.write_text("raw-clock some/file.py\n")  # no rationale
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "hhmm_tpu.analysis",
                "--allowlist",
                str(bad),
                "hhmm_tpu/analysis",
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        assert proc.returncode == 2
        assert "rationale" in proc.stderr

    def test_analyzer_never_imports_jax(self):
        """The analyzer must run on jax-less hosts and inside tier-1
        without paying a jax import — asserted statically over the
        whole package (the obs_report discipline)."""
        pkg = os.path.join(REPO, "hhmm_tpu", "analysis")
        for name in sorted(os.listdir(pkg)):
            if not name.endswith(".py"):
                continue
            src = open(os.path.join(pkg, name)).read()
            for node in ast.walk(ast.parse(src)):
                if isinstance(node, ast.Import):
                    roots = [a.name.split(".")[0] for a in node.names]
                else:
                    roots = (
                        [(node.module or "").split(".")[0]]
                        if isinstance(node, ast.ImportFrom) and node.level == 0
                        else []
                    )
                for r in roots:
                    assert r != "jax", f"{name}: imports jax"
                    assert r != "numpy", f"{name}: imports numpy"


class TestShimContract:
    """scripts/check_guards.py must keep the legacy monolith's
    exit-code and message contract — the same toy trees the legacy
    suite (test_robust/test_obs/test_plan) pins, re-asserted here as
    the shim's own regression."""

    def _run_on(self, root):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "check_guards.py"), str(root)],
            capture_output=True,
            text=True,
        )

    def test_repo_exits_zero_with_legacy_ok_line(self, check_guards_repo):
        proc = check_guards_repo  # one shared repo scan (conftest)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        for phrase in (
            "check_guards: ok",
            "monotonic clocks",
            "one shared metrics plane",
            "placement objects confined",
        ):
            assert phrase in proc.stdout

    def test_violating_tree_exits_one_with_legacy_lines(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        (pkg / "infer").mkdir(parents=True)
        (pkg / "bad.py").write_text("try:\n    pass\nexcept:\n    pass\n")
        (pkg / "infer" / "run.py").write_text("def sample_nuts():\n    pass\n")
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "bare `except:`" in proc.stdout
        assert "chain-health guard" in proc.stdout
        assert "violation(s)" in proc.stdout

    def test_missing_package_exits_one(self, tmp_path):
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "no hhmm_tpu/ package" in proc.stdout

    def test_new_rules_flow_through_shim(self, tmp_path):
        (tmp_path / "hhmm_tpu" / "kernels").mkdir(parents=True)
        (tmp_path / "hhmm_tpu" / "kernels" / "toy.py").write_text(
            "import jax.numpy as jnp\n\nZ = jnp.zeros((3,))\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "dtype-less" in proc.stdout

    def test_warnings_stay_out_of_shim_stream(self, tmp_path):
        # legacy contract: "N violation(s)" == printed lines, and the
        # ok line means ALL printed checks are clean — so a
        # warnings-only tree prints no finding lines and exits 0
        # (the real CLI surfaces warnings)
        (tmp_path / "hhmm_tpu" / "infer").mkdir(parents=True)
        (tmp_path / "hhmm_tpu" / "infer" / "toy.py").write_text(
            "import jax\n\n"
            "def f(key):\n"
            "    k1, k2 = jax.random.split(key)\n"
            "    return jax.random.normal(k1, (3,))\n"
        )
        proc = self._run_on(tmp_path)
        # the toy tree trips OTHER module-missing invariants, so rc is
        # 1 — but no dead-split line leaks into the legacy stream and
        # the violation count equals the printed finding lines
        assert "dead PRNG split" not in proc.stdout
        n = int(proc.stdout.rsplit("check_guards: ", 1)[1].split()[0])
        lines = [
            l
            for l in proc.stdout.splitlines()
            if l and not l.startswith("check_guards:")
        ]
        assert n == len(lines)


class TestObsReportAnalysisSection:
    FIXTURES = os.path.join(REPO, "tests", "fixtures")

    def test_fixture_manifest_renders_analysis_section(self):
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "obs_report.py"),
                os.path.join(self.FIXTURES, "obs_report_manifest.json"),
            ],
            capture_output=True,
            text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "== analysis ==" in proc.stdout
        assert "suppressed: 5" in proc.stdout
        assert "CLEAN (zero unsuppressed findings)" in proc.stdout
        # per-family rollup + the lock-order verdict (ISSUE 12)
        assert "concurrency" in proc.stdout
        assert "lock-order: ACYCLIC" in proc.stdout
        assert "locks: 9" in proc.stdout

    def test_analysis_flag_overrides_stanza(self, tmp_path):
        report = {
            "version": 1,
            "files_scanned": 2,
            "rules": {"raw-clock": {"severity": "error", "findings": 1, "suppressed": 0}},
            "findings": [
                {
                    "file": "hhmm_tpu/apps/x.py",
                    "line": 4,
                    "rule_id": "raw-clock",
                    "severity": "error",
                    "message": "raw read",
                }
            ],
            "suppressed_count": 0,
            "allowlist_entries": 0,
            "allowlist_unused": [],
            "ok": False,
        }
        rp = tmp_path / "analysis.json"
        rp.write_text(json.dumps(report))
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "obs_report.py"),
                os.path.join(self.FIXTURES, "obs_report_manifest.json"),
                "--analysis",
                str(rp),
            ],
            capture_output=True,
            text=True,
        )
        assert proc.returncode == 0
        assert "verdict: FINDINGS" in proc.stdout
        assert "hhmm_tpu/apps/x.py:4: [raw-clock]" in proc.stdout

    def test_missing_stanza_degrades(self, tmp_path):
        man = tmp_path / "man.json"
        man.write_text(json.dumps({"version": 1}))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"), str(man)],
            capture_output=True,
            text=True,
        )
        assert proc.returncode == 0
        assert "(no static-analysis report in this run)" in proc.stdout


# ---------------------------------------------------------------------------
# concurrency family (ISSUE 12)


_LOCK_CYCLE = (
    "import threading\n"
    "\n"
    "LOCK_A = threading.Lock()\n"
    "LOCK_B = threading.Lock()\n"
    "\n"
    "def ab():\n"
    "    with LOCK_A:\n"
    "        with LOCK_B:\n"
    "            pass\n"
    "\n"
    "def ba():\n"
    "    with LOCK_B:\n"
    "        with LOCK_A:\n"
    "            pass\n"
)

_LOCK_ORDERED = (
    "import threading\n"
    "\n"
    "LOCK_A = threading.Lock()\n"
    "LOCK_B = threading.Lock()\n"
    "\n"
    "def ab():\n"
    "    with LOCK_A:\n"
    "        with LOCK_B:\n"
    "            pass\n"
    "\n"
    "def ab_again():\n"
    "    with LOCK_A:\n"
    "        with LOCK_B:\n"
    "            pass\n"
)


class TestLockOrder:
    def test_cycle_fires_and_dag_reports_it(self, tmp_path):
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": _LOCK_CYCLE}, ["lock-order"])
        hits = _fires(rep, "lock-order")
        assert hits and "cycle" in hits[0].message
        dag = rep.extras["lock_order"]
        assert dag["verdict"] == "CYCLES"
        assert len(dag["edges"]) == 2
        assert dag["cycles"]

    def test_consistent_order_silent_with_edge_recorded(self, tmp_path):
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": _LOCK_ORDERED}, ["lock-order"])
        assert not _fires(rep, "lock-order")
        dag = rep.extras["lock_order"]
        assert dag["verdict"] == "ACYCLIC"
        assert len(dag["edges"]) == 1
        assert dag["edges"][0]["from"].endswith("::LOCK_A")
        assert dag["edges"][0]["to"].endswith("::LOCK_B")

    def test_interprocedural_cycle_through_helpers(self, tmp_path):
        src = (
            "import threading\n"
            "\n"
            "LOCK_A = threading.Lock()\n"
            "LOCK_B = threading.Lock()\n"
            "\n"
            "def take_b():\n"
            "    with LOCK_B:\n"
            "        pass\n"
            "\n"
            "def take_a():\n"
            "    with LOCK_A:\n"
            "        pass\n"
            "\n"
            "def ab():\n"
            "    with LOCK_A:\n"
            "        take_b()\n"
            "\n"
            "def ba():\n"
            "    with LOCK_B:\n"
            "        take_a()\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["lock-order"])
        assert _fires(rep, "lock-order")
        assert rep.extras["lock_order"]["verdict"] == "CYCLES"

    def test_cross_module_edge_resolves(self, tmp_path):
        sub = (
            "import threading\n"
            "\n"
            "_LOCK = threading.Lock()\n"
            "\n"
            "def publish():\n"
            "    with _LOCK:\n"
            "        pass\n"
        )
        top = (
            "import threading\n"
            "from hhmm_tpu.obs import toymetrics\n"
            "\n"
            "_TOP = threading.Lock()\n"
            "\n"
            "def flush():\n"
            "    with _TOP:\n"
            "        toymetrics.publish()\n"
        )
        rep = _run(
            tmp_path,
            {
                "hhmm_tpu/obs/toymetrics.py": sub,
                "hhmm_tpu/serve/toy.py": top,
            },
            ["lock-order"],
        )
        assert not _fires(rep, "lock-order")
        edges = rep.extras["lock_order"]["edges"]
        assert any(
            e["from"] == "hhmm_tpu/serve/toy.py::_TOP"
            and e["to"] == "hhmm_tpu/obs/toymetrics.py::_LOCK"
            for e in edges
        )

    def test_self_deadlock_through_method_call(self, tmp_path):
        src = (
            "import threading\n"
            "\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def inner(self):\n"
            "        with self._lock:\n"
            "            pass\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            self.inner()\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["lock-order"])
        hits = _fires(rep, "lock-order")
        assert hits and "self-deadlock" in hits[0].message

    def test_rlock_reentry_silent(self, tmp_path):
        src = (
            "import threading\n"
            "\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "    def inner(self):\n"
            "        with self._lock:\n"
            "            pass\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            self.inner()\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["lock-order"])
        assert not _fires(rep, "lock-order")

    def test_acquire_release_spelling(self, tmp_path):
        src = (
            "import threading\n"
            "\n"
            "LOCK_A = threading.Lock()\n"
            "LOCK_B = threading.Lock()\n"
            "\n"
            "def f():\n"
            "    LOCK_A.acquire()\n"
            "    with LOCK_B:\n"
            "        pass\n"
            "    LOCK_A.release()\n"
            "\n"
            "def g():\n"
            "    with LOCK_B:\n"
            "        LOCK_A.acquire()\n"
            "        LOCK_A.release()\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["lock-order"])
        assert _fires(rep, "lock-order")
        assert rep.extras["lock_order"]["verdict"] == "CYCLES"


class TestSharedStateRace:
    def test_guarded_attr_mutated_unlocked_fires(self, tmp_path):
        src = (
            "import threading\n"
            "\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def good(self, x):\n"
            "        with self._lock:\n"
            "            self._items.append(x)\n"
            "    def bad(self, x):\n"
            "        self._items.append(x)\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["shared-state-race"])
        hits = _fires(rep, "shared-state-race")
        assert len(hits) == 1
        assert hits[0].line == 11 and "_items" in hits[0].message

    def test_all_locked_and_init_silent(self, tmp_path):
        src = (
            "import threading\n"
            "\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def put(self, x):\n"
            "        with self._lock:\n"
            "            self._items.append(x)\n"
            "    def reset(self):\n"
            "        with self._lock:\n"
            "            self._items = []\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["shared-state-race"])
        assert not _fires(rep, "shared-state-race")

    def test_lock_dominated_helper_silent(self, tmp_path):
        # the Tracer._append pattern: every call site holds the lock
        src = (
            "import threading\n"
            "\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def put(self, x):\n"
            "        with self._lock:\n"
            "            self._append(x)\n"
            "    def put2(self, x):\n"
            "        with self._lock:\n"
            "            self._append(x)\n"
            "    def _append(self, x):\n"
            "        self._items.append(x)\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["shared-state-race"])
        assert not _fires(rep, "shared-state-race")

    def test_unlocked_helper_call_site_fires(self, tmp_path):
        # one unlocked call site breaks the domination
        src = (
            "import threading\n"
            "\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def put(self, x):\n"
            "        with self._lock:\n"
            "            self._append(x)\n"
            "    def sneak(self, x):\n"
            "        self._append(x)\n"
            "    def _append(self, x):\n"
            "        self._items.append(x)\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["shared-state-race"])
        assert _fires(rep, "shared-state-race")

    def test_threading_local_attr_silent(self, tmp_path):
        src = (
            "import threading\n"
            "\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._tls = threading.local()\n"
            "        self._items = []\n"
            "    def put(self, x):\n"
            "        with self._lock:\n"
            "            self._items.append(x)\n"
            "    def stack(self):\n"
            "        self._tls.stack = []\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["shared-state-race"])
        assert not _fires(rep, "shared-state-race")

    def test_module_container_unlocked_fires(self, tmp_path):
        src = (
            "CACHE = {}\n"
            "\n"
            "def put(k, v):\n"
            "    CACHE[k] = v\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["shared-state-race"])
        hits = _fires(rep, "shared-state-race")
        assert hits and "CACHE" in hits[0].message and hits[0].line == 4

    def test_module_container_under_lock_silent(self, tmp_path):
        src = (
            "import threading\n"
            "\n"
            "CACHE = {}\n"
            "_LOCK = threading.Lock()\n"
            "\n"
            "def put(k, v):\n"
            "    with _LOCK:\n"
            "        CACHE[k] = v\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["shared-state-race"])
        assert not _fires(rep, "shared-state-race")

    def test_module_threading_local_silent(self, tmp_path):
        src = (
            "import threading\n"
            "\n"
            "_TLS = threading.local()\n"
            "\n"
            "def put(v):\n"
            "    _TLS.stack = [v]\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["shared-state-race"])
        assert not _fires(rep, "shared-state-race")

    def test_pragma_single_thread_contract(self, tmp_path):
        src = (
            "CACHE = {}\n"
            "\n"
            "def put(k, v):\n"
            "    CACHE[k] = v  # lint: ok shared-state-race -- single-thread contract: test-only\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["shared-state-race"])
        assert not _fires(rep, "shared-state-race")
        assert rep.suppressed


class TestHeldLockEscape:
    def test_bad_fixture_fires_each_category(self, tmp_path):
        src = (
            "import threading\n"
            "import time\n"
            "import jax.numpy as jnp\n"
            "\n"
            "_LOCK = threading.Lock()\n"
            "\n"
            "def bad(x, on_done):\n"
            "    with _LOCK:\n"
            "        y = jnp.exp(x)\n"
            "        y.block_until_ready()\n"
            "        open('/tmp/x.txt')\n"
            "        time.sleep(0.1)\n"
            "        on_done()\n"
            "    return y\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["held-lock-escape"])
        msgs = "\n".join(f.message for f in _fires(rep, "held-lock-escape"))
        assert "jax dispatch" in msgs
        assert "block_until_ready" in msgs
        assert "file" in msgs
        assert "sleep" in msgs
        assert "callback" in msgs
        assert "acquired at line 8" in msgs

    def test_good_fixture_silent(self, tmp_path):
        src = (
            "import threading\n"
            "import time\n"
            "import jax.numpy as jnp\n"
            "\n"
            "_LOCK = threading.Lock()\n"
            "\n"
            "def good(x, on_done):\n"
            "    y = jnp.exp(x)\n"
            "    y.block_until_ready()\n"
            "    with _LOCK:\n"
            "        z = [y]\n"
            "    time.sleep(0.1)\n"
            "    on_done()\n"
            "    return z\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["held-lock-escape"])
        assert not _fires(rep, "held-lock-escape")

    def test_interprocedural_callee_io_fires(self, tmp_path):
        src = (
            "import threading\n"
            "\n"
            "_LOCK = threading.Lock()\n"
            "\n"
            "def write_out(p):\n"
            "    open(p)\n"
            "\n"
            "def bad(p):\n"
            "    with _LOCK:\n"
            "        write_out(p)\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["held-lock-escape"])
        hits = _fires(rep, "held-lock-escape")
        assert hits and "write_out" in hits[0].message


class TestAtomicWrite:
    def test_text_write_and_write_text_fire(self, tmp_path):
        src = (
            "def dump(p, q, text):\n"
            "    with open(p, 'w') as f:\n"
            "        f.write(text)\n"
            "    q.write_text(text)\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/serve/toy.py": src}, ["atomic-write"])
        hits = _fires(rep, "atomic-write")
        assert len(hits) == 2
        assert {h.line for h in hits} == {2, 4}

    def test_reads_binary_and_trace_exempt(self, tmp_path):
        src = (
            "def ok(p):\n"
            "    with open(p) as f:\n"
            "        a = f.read()\n"
            "    with open(p, 'rb') as f:\n"
            "        b = f.read()\n"
            "    with open(p, 'wb') as f:\n"
            "        f.write(b'x')\n"
            "    return a, b\n"
        )
        trace_src = "def atomic(p, text):\n    with open(p, 'w') as f:\n        f.write(text)\n"
        rep = _run(
            tmp_path,
            {
                "hhmm_tpu/serve/toy.py": src,
                "hhmm_tpu/obs/trace.py": trace_src,
            },
            ["atomic-write"],
        )
        assert not _fires(rep, "atomic-write")


class TestPragmaStatementAnchor:
    BAD = (
        "import numpy as np\n"
        "def f(x):\n"
        "    return np.asarray(  # lint: ok dtype-float64 -- multi-line anchor test\n"
        "        x,\n"
        "        np.float64,\n"
        "    )\n"
    )

    def test_pragma_on_statement_first_line_suppresses(self, tmp_path):
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": self.BAD}, ["dtype-float64"])
        assert not _fires(rep, "dtype-float64")
        assert rep.suppressed and rep.suppressed[0].line == 5

    def test_wrong_rule_id_on_first_line_does_not_suppress(self, tmp_path):
        src = self.BAD.replace("dtype-float64 --", "raw-clock --")
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["dtype-float64"])
        hits = _fires(rep, "dtype-float64")
        assert hits and hits[0].line == 5

    def test_def_line_pragma_does_not_blanket_the_body(self, tmp_path):
        # the statement anchor is the INNERMOST statement: a pragma on
        # the def line must not suppress findings inside the body
        src = (
            "import numpy as np\n"
            "def f(x):  # lint: ok dtype-float64 -- must not blanket\n"
            "    y = 1\n"
            "    return np.asarray(x, np.float64)\n"
        )
        rep = _run(tmp_path, {"hhmm_tpu/kernels/toy.py": src}, ["dtype-float64"])
        assert _fires(rep, "dtype-float64")


class TestRatchet:
    WARN = (
        "import jax\n"
        "\n"
        "def f(key):\n"
        "    k1, k2 = jax.random.split(key)\n"
        "    return jax.random.normal(k1, (3,))\n"
    )

    def _cli(self, root, *extra):
        return subprocess.run(
            [
                sys.executable,
                "-m",
                "hhmm_tpu.analysis",
                "--root",
                str(root),
                "--rules",
                "prng-dead-split",
                "hhmm_tpu",
                *extra,
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
        )

    def test_new_finding_fails_update_then_passes_then_tightens(self, tmp_path):
        (tmp_path / "hhmm_tpu" / "infer").mkdir(parents=True)
        toy = tmp_path / "hhmm_tpu" / "infer" / "toy.py"
        toy.write_text(self.WARN)
        base = tmp_path / "baseline.json"

        # warnings alone don't fail ...
        proc = self._cli(tmp_path)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        # ... but the ratchet does: vs a missing baseline every finding
        # is NEW
        proc = self._cli(tmp_path, "--baseline", str(base))
        assert proc.returncode == 1
        assert "NEW finding" in proc.stdout
        assert "prng-dead-split hhmm_tpu/infer/toy.py: 0 -> 1" in proc.stdout

        # accept deliberately
        proc = self._cli(tmp_path, "--baseline", str(base), "--update-baseline")
        assert proc.returncode == 0
        doc = json.loads(base.read_text())
        assert doc["counts"] == {"prng-dead-split hhmm_tpu/infer/toy.py": 1}

        # now the scan matches the baseline
        proc = self._cli(tmp_path, "--baseline", str(base))
        assert proc.returncode == 0
        assert "match the baseline" in proc.stdout

        # fixing the finding flips to "tighten it"
        toy.write_text(self.WARN.replace("k1, k2 = jax.random.split(key)\n    ", ""))
        proc = self._cli(tmp_path, "--baseline", str(base))
        assert proc.returncode == 0
        assert "improved on the baseline" in proc.stdout
        assert "--update-baseline" in proc.stdout

    def test_malformed_baseline_exits_two(self, tmp_path):
        (tmp_path / "hhmm_tpu").mkdir()
        (tmp_path / "hhmm_tpu" / "toy.py").write_text("X = 1\n")
        base = tmp_path / "baseline.json"
        base.write_text("{not json")
        proc = self._cli(tmp_path, "--baseline", str(base))
        assert proc.returncode == 2
        assert "baseline" in proc.stderr

    def test_update_without_baseline_exits_two(self, tmp_path):
        (tmp_path / "hhmm_tpu").mkdir()
        (tmp_path / "hhmm_tpu" / "toy.py").write_text("X = 1\n")
        proc = self._cli(tmp_path, "--update-baseline")
        assert proc.returncode == 2

    def test_repo_baseline_matches(self):
        # the checked-in baseline is live: make lint runs against it.
        # Restricted to one cheap rule — the point is the baseline
        # load + diff + exit-code wiring against the REAL checked-in
        # file, not a third full repo scan (the full scan's zero
        # findings are already pinned by TestRepoCleanConcurrency,
        # and zero findings for ANY rule subset matches the empty
        # baseline the same way)
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "hhmm_tpu.analysis",
                "--rules",
                "atomic-write",
                "--baseline",
                "results/analysis_baseline.json",
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ratchet" in proc.stdout


class TestLintChanged:
    """`scripts/lint.py --changed` must scan renamed files under their
    NEW path and never hand the engine a deleted path (ISSUE 12
    satellite; regression for the `git status --porcelain` parser)."""

    def _git(self, repo, *args):
        subprocess.run(
            ["git", "-C", str(repo), *args],
            check=True,
            capture_output=True,
            env={
                **os.environ,
                "GIT_AUTHOR_NAME": "t",
                "GIT_AUTHOR_EMAIL": "t@t",
                "GIT_COMMITTER_NAME": "t",
                "GIT_COMMITTER_EMAIL": "t@t",
            },
        )

    def test_renamed_and_deleted_working_tree(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "a.py").write_text(
            "def f():\n    try:\n        pass\n    except:\n        pass\n"
        )
        (pkg / "b.py").write_text("Y = 2\n")
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", "-A")
        self._git(tmp_path, "commit", "-qm", "seed")
        # rename a.py (staged), delete b.py (unstaged), add untracked
        self._git(tmp_path, "mv", "hhmm_tpu/a.py", "hhmm_tpu/renamed.py")
        (pkg / "b.py").unlink()
        (pkg / "fresh.py").write_text("Z = 3\n")

        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "lint.py"),
                "--changed",
                "--repo",
                str(tmp_path),
                "--rules",
                "bare-except",
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        # the renamed file is scanned under its NEW path and still
        # carries its finding; the deleted path never reaches the
        # engine (no crash, no phantom file)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "hhmm_tpu/renamed.py" in proc.stdout
        assert "b.py" not in proc.stdout
        assert "2 file(s)" in proc.stdout

    def test_clean_tree_no_changed_files(self, tmp_path):
        (tmp_path / "hhmm_tpu").mkdir()
        (tmp_path / "hhmm_tpu" / "a.py").write_text("X = 1\n")
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", "-A")
        self._git(tmp_path, "commit", "-qm", "seed")
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "lint.py"),
                "--changed",
                "--repo",
                str(tmp_path),
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "no changed .py files" in proc.stdout


class TestRepoCleanConcurrency:
    """ISSUE 12 acceptance: the concurrency family is enabled, the
    repo scans clean, the lock-order graph is acyclic, and the full
    scan stays inside the tier-1 <10 s budget. ONE timed full scan
    carries every assertion — the suite must not pay three repo scans
    for one acceptance criterion (tier-1 duration-ledger discipline)."""

    def test_concurrency_rules_registered(self):
        for rid in (
            "lock-order",
            "shared-state-race",
            "held-lock-escape",
            "atomic-write",
        ):
            assert rid in RULES
            assert RULES[rid].family == "concurrency"

    def test_repo_clean_acyclic_and_under_ten_seconds(self):
        import time as _time

        t0 = _time.perf_counter()
        rep = run_analysis(root=REPO)  # ALL rules, concurrency included
        dt = _time.perf_counter() - t0
        assert rep.findings == [], "\n".join(f.format() for f in rep.findings)
        assert {
            "lock-order",
            "shared-state-race",
            "held-lock-escape",
            "atomic-write",
        } <= set(rep.rules_run)
        dag = rep.extras["lock_order"]
        assert dag["verdict"] == "ACYCLIC" and not dag["cycles"]
        # the PR 12 pager lock is a tracked node, and the leaf-only
        # property documented in docs/architecture.md holds
        assert "hhmm_tpu/serve/pager.py::SnapshotPager._lock" in dag["locks"]
        assert len(dag["locks"]) >= 12
        assert dag["edges"] == []
        assert dt < 10.0, f"full scan took {dt:.1f}s (tier-1 budget is <10s)"
