"""Adaptation-plane suite (`hhmm_tpu/adapt/`, docs/maintenance.md's
three-rung ladder — tier-1, fast).

Pins the subsystem's contracts:

- **weight math** (`adapt/weights.py`): normalized log-weight updates
  with forgetting, dead-draw ``-inf`` discipline with the all-dead
  uniform restart, streaming ESS bounds, and the weighted/uniform
  mixture predictives the bench duels;
- **Liu–West kernel** (`adapt/rejuvenate.py`): shape/dtype/draw-count
  preservation, PRNG determinism, dead draws never resampled, the
  all-dead passthrough, degenerate-weight collapse toward the
  surviving particle;
- **ladder** (`adapt/ladder.py`): reweight on observe (sheds never
  touch weights), ESS-floor rejuvenation, the strike sequence
  rejuvenate→rejuvenate→escalate, promotion clearing strikes, the
  manifest stanza;
- **maintenance routing** (`maint/loop.py`): a fresh CUSUM alarm is
  consumed by the ladder; an escalated alarm falls through to the
  refit queue; an OWED alarm never re-enters the ladder;
- **weight-state lifecycle** (scheduler surface): survives
  detach→warm page-in bitwise, reset by ``swap_snapshot``'s committed
  attach, released by ``unregister``, never created for shed ticks;
  a REJUVENATED bank's weights are dropped on detach (the paged-in
  snapshot is not the bank they were learned on);
- **weighted forecasts** (`serve/online.py`): fractional
  ``posterior_predictive_mean`` weights are honored (not binarized),
  non-finite draws are zeroed, zero-mass weights fall back to the
  finite draws, and only a no-finite-draw series yields NaN.
"""

import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hhmm_tpu.adapt import (
    AdaptationLadder,
    Rejuvenator,
    ess,
    liu_west_move,
    normalized_weights,
    uniform_log_weights,
    uniform_mixture_loglik,
    update_log_weights,
    weighted_mixture_loglik,
    weighted_state_probs,
)
from hhmm_tpu.models import MultinomialHMM
from hhmm_tpu.serve import (
    MicroBatchScheduler,
    PosteriorSnapshot,
    SnapshotRegistry,
    model_spec,
    posterior_predictive_mean,
)
from hhmm_tpu.serve.scheduler import TickResponse

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fake_snapshot(model, n_draws=6, scale=0.3, seed=0, healthy=True):
    rng = np.random.default_rng(seed)
    draws = (rng.normal(size=(n_draws, model.n_free)) * scale).astype(
        np.float32
    )
    return PosteriorSnapshot(
        spec=model_spec(model), draws=draws, healthy=healthy
    )


def _attached_sched(n_draws=4, history_tail=16, sid="s", buckets=(4,)):
    """One MultinomialHMM series attached and ticked twice — the
    minimal state every adaptation surface needs (a bank, a filter,
    per-draw increments)."""
    model = MultinomialHMM(K=2, L=3)
    sched = MicroBatchScheduler(
        model, buckets=buckets, history_tail=history_tail
    )
    sched.attach(sid, _fake_snapshot(model, n_draws=n_draws))
    for t in range(2):
        r = sched.tick({sid: {"x": t % 3}})[sid]
        assert not r.shed
    return model, sched, r


class TestWeights:
    def test_uniform_is_normalized_and_ess_is_d(self):
        lw = uniform_log_weights(8)
        assert lw.shape == (8,) and lw.dtype == np.float32
        np.testing.assert_allclose(np.exp(lw).sum(), 1.0, rtol=1e-6)
        np.testing.assert_allclose(float(ess(lw)), 8.0, rtol=1e-5)

    def test_update_tilts_toward_better_draws(self):
        inc = np.array([0.0, 0.0, 2.0, 0.0], np.float32)
        lw = np.asarray(update_log_weights(None, inc))
        w = normalized_weights(lw)
        np.testing.assert_allclose(w.sum(), 1.0, rtol=1e-6)
        assert np.argmax(w) == 2 and w[2] > 0.5
        # a second identical increment sharpens further; ESS drops
        lw2 = np.asarray(update_log_weights(lw, inc))
        assert normalized_weights(lw2)[2] > w[2]
        assert float(ess(lw2)) < float(ess(lw)) < 4.0

    def test_forgetting_widens_the_window(self):
        """forget < 1 discounts accumulated evidence: after the same
        increments, the tempered weights are closer to uniform (higher
        ESS) than the full-history ones."""
        inc = np.array([0.0, 0.0, 1.5], np.float32)
        full = tempered = None
        for _ in range(6):
            full = update_log_weights(full, inc, forget=1.0)
            tempered = update_log_weights(tempered, inc, forget=0.5)
        assert float(ess(tempered)) > float(ess(full))

    @pytest.mark.parametrize("bad", [0.0, -0.1, 1.5])
    def test_forget_validation(self, bad):
        with pytest.raises(ValueError, match="forget"):
            update_log_weights(None, np.zeros(3, np.float32), forget=bad)

    def test_dead_draws_pinned_at_neg_inf(self):
        inc = np.array([0.0, np.nan, 0.0, np.inf], np.float32)
        ok = np.array([True, True, False, True])
        lw = np.asarray(update_log_weights(None, inc, ok))
        # non-finite increment (1, 3) and unhealthy (2) are all dead
        assert np.isneginf(lw[[1, 2, 3]]).all() and np.isfinite(lw[0])
        # dead draws stay dead through later updates and forgetting
        lw2 = np.asarray(
            update_log_weights(lw, np.zeros(4, np.float32), forget=0.9)
        )
        assert np.isneginf(lw2[[1, 2, 3]]).all()
        assert normalized_weights(lw2)[0] == 1.0
        np.testing.assert_allclose(float(ess(lw2)), 1.0, rtol=1e-5)

    def test_all_dead_resets_to_uniform(self):
        inc = np.full(4, np.nan, np.float32)
        lw = np.asarray(update_log_weights(None, inc))
        np.testing.assert_allclose(lw, uniform_log_weights(4), rtol=1e-6)
        # but the pure all--inf vector reports ESS 0 (nothing alive)
        assert float(ess(np.full(4, -np.inf, np.float32))) == 0.0

    def test_mixture_logliks(self):
        inc = np.array([1.0, -1.0, 0.0, np.nan], np.float32)
        ok = np.array([True, True, False, True])
        u = float(uniform_mixture_loglik(inc, ok))
        # uniform over the 2 alive draws: logsumexp([1,-1]) - log 2
        expect = np.log((np.exp(1.0) + np.exp(-1.0)) / 2.0)
        np.testing.assert_allclose(u, expect, rtol=1e-5)
        # with every draw alive, uniform weights ARE the uniform mixture
        alive_inc = np.array([1.0, -1.0, 0.5, 0.0], np.float32)
        np.testing.assert_allclose(
            float(weighted_mixture_loglik(uniform_log_weights(4), alive_inc)),
            float(uniform_mixture_loglik(alive_inc)),
            rtol=1e-5,
        )
        # with dead draws, the weighted mixture SHEDS their mass (no
        # renormalization — a dead draw's weight is lost evidence),
        # here exactly the 2-of-4 alive fraction below the renormalized
        # uniform baseline
        lw = uniform_log_weights(4)
        np.testing.assert_allclose(
            float(weighted_mixture_loglik(lw, inc, ok)),
            u - np.log(2.0),
            rtol=1e-5,
        )
        # tilting toward the better draw beats the uniform mixture
        tilt = np.log(
            np.array([0.9, 0.1 / 3, 0.1 / 3, 0.1 / 3], np.float32)
        )
        assert float(weighted_mixture_loglik(tilt, inc, ok)) > u
        # an all-dead cloud is -inf evidence, never NaN
        dead = np.full(4, np.nan, np.float32)
        assert np.isneginf(float(uniform_mixture_loglik(dead)))
        assert np.isneginf(float(weighted_mixture_loglik(lw, dead)))

    def test_weighted_state_probs(self):
        la = np.log(
            np.array([[0.9, 0.1], [0.2, 0.8]], np.float32)
        )  # [D=2, K=2]
        # uniform weights = plain draw average
        p = weighted_state_probs(uniform_log_weights(2), la)
        np.testing.assert_allclose(p, [0.55, 0.45], rtol=1e-5)
        np.testing.assert_allclose(p.sum(), 1.0, rtol=1e-6)
        # a one-hot weight selects its draw's filter
        one_hot = np.array([0.0, -np.inf], np.float32)
        np.testing.assert_allclose(
            weighted_state_probs(one_hot, la), [0.9, 0.1], rtol=1e-5
        )

    def test_normalized_weights_zero_for_dead(self):
        lw = np.array([0.0, -np.inf, 0.0], np.float32)
        w = normalized_weights(lw)
        assert w[1] == 0.0
        np.testing.assert_allclose(w, [0.5, 0.0, 0.5], rtol=1e-6)


class TestRejuvenator:
    def _cloud(self, rng, n=2, d=6, p=5, k=3):
        draws = rng.normal(size=(n, d, p)).astype(np.float32)
        lw = rng.normal(size=(n, d)).astype(np.float32)
        alpha = rng.normal(size=(n, d, k)).astype(np.float32)
        ll = rng.normal(size=(n, d)).astype(np.float32)
        ok = np.ones((n, d), bool)
        return draws, lw, alpha, ll, ok

    def test_shapes_dtypes_preserved_and_deterministic(self, rng):
        draws, lw, alpha, ll, ok = self._cloud(rng)
        r1 = Rejuvenator(jax.random.PRNGKey(0))
        r2 = Rejuvenator(jax.random.PRNGKey(0))
        out1 = r1.move(draws, lw, alpha, ll, ok)
        out2 = r2.move(draws, lw, alpha, ll, ok)
        for a, b, ref in zip(out1, out2, (draws, alpha, ll, ok)):
            assert a.shape == ref.shape and a.dtype == ref.dtype
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the cloud actually moved (resample + jitter)
        assert not np.array_equal(np.asarray(out1[0]), draws)
        # the owned key advances: a second move differs from the first
        out3 = r1.move(draws, lw, alpha, ll, ok)
        assert not np.array_equal(np.asarray(out3[0]), np.asarray(out1[0]))

    def test_degenerate_weights_collapse_to_winner(self, rng):
        """One-hot weights: every resampled particle descends from the
        winning draw — shrunk toward it (the weighted mean IS the
        winner) plus kernel noise scaled by the weighted variance,
        which is 0 for a point mass, so the move is exact."""
        draws, _, alpha, ll, ok = self._cloud(rng, n=1)
        lw = np.full((1, 6), -np.inf, np.float32)
        lw[0, 4] = 0.0
        nd, na, nl, nk = Rejuvenator(jax.random.PRNGKey(3)).move(
            draws, lw, alpha, ll, ok
        )
        np.testing.assert_allclose(
            np.asarray(nd), np.broadcast_to(draws[:, 4:5], draws.shape),
            rtol=0, atol=1e-5,
        )
        np.testing.assert_array_equal(
            np.asarray(na), np.broadcast_to(alpha[:, 4:5], alpha.shape)
        )

    def test_dead_draws_never_resampled(self, rng):
        """Even with the HIGHEST log-weight, an ok=False draw cannot
        appear in the rejuvenated cloud's ancestry."""
        draws, _, alpha, ll, ok = self._cloud(rng, n=1)
        draws[0, 2] = 100.0  # a poisoned, easily recognizable draw
        lw = np.zeros((1, 6), np.float32)
        lw[0, 2] = 50.0  # weight says "take me"
        ok[0, 2] = False  # health says never
        nd, _, _, nk = Rejuvenator(jax.random.PRNGKey(4)).move(
            draws, lw, alpha, ll, ok
        )
        assert np.asarray(nd).max() < 50.0
        assert np.asarray(nk).all()  # survivors are all healthy lanes

    def test_all_dead_cloud_passes_through(self, rng):
        draws, lw, alpha, ll, ok = self._cloud(rng, n=2)
        ok[1] = False  # series 1: nothing alive to resample
        nd, na, nl, nk = Rejuvenator(jax.random.PRNGKey(5)).move(
            draws, lw, alpha, ll, ok
        )
        assert not np.array_equal(np.asarray(nd[0]), draws[0])
        np.testing.assert_array_equal(np.asarray(nd[1]), draws[1])
        np.testing.assert_array_equal(np.asarray(na[1]), alpha[1])
        np.testing.assert_array_equal(np.asarray(nk[1]), ok[1])

    @pytest.mark.parametrize("bad", [0.0, 1.0, -0.5, 1.5])
    def test_shrink_validation(self, bad):
        with pytest.raises(ValueError, match="shrink"):
            Rejuvenator(jax.random.PRNGKey(0), shrink=bad)

    def test_keeps_weighted_moments_approximately(self, rng):
        """The Liu–West identity: the rejuvenated cloud's mean tracks
        the weighted mean, and its spread does not explode (a·V + h²·V
        = V in expectation)."""
        d, p = 256, 3
        draws = rng.normal(size=(1, d, p)).astype(np.float32)
        lw = rng.normal(size=(1, d)).astype(np.float32)
        alpha = np.zeros((1, d, 2), np.float32)
        ll = np.zeros((1, d), np.float32)
        ok = np.ones((1, d), bool)
        (nd,) = Rejuvenator(jax.random.PRNGKey(7)).move(
            draws, lw, alpha, ll, ok
        )[:1]
        w = np.exp(lw[0] - lw[0].max())
        w /= w.sum()
        m = (w[:, None] * draws[0]).sum(0)
        v = (w[:, None] * (draws[0] - m) ** 2).sum(0)
        nd = np.asarray(nd[0])
        np.testing.assert_allclose(nd.mean(0), m, atol=4 * np.sqrt(v / d).max())
        assert (nd.var(0) < 3 * v).all()


def _resp(sid, inc, ok=None, shed=False):
    """A minimal synthetic TickResponse for ladder-unit tests."""
    d = 0 if inc is None else len(inc)
    return TickResponse(
        series_id=sid,
        probs=np.array([0.5, 0.5]),
        loglik=0.0,
        healthy_draws=d,
        degraded=False,
        latency_s=0.0,
        shed=shed,
        per_draw_loglik=None if inc is None else np.asarray(inc, np.float32),
        draw_ok=None if inc is None else (
            np.ones(d, bool) if ok is None else np.asarray(ok, bool)
        ),
    )


class TestAdaptationLadder:
    def test_observe_reweights_and_skips_sheds(self):
        model, sched, _ = _attached_sched(n_draws=4)
        ladder = AdaptationLadder(sched, jax.random.PRNGKey(0))
        inc = np.array([0.5, 0.0, 0.0, 0.0], np.float32)
        n = ladder.observe(
            [
                _resp("s", inc),
                _resp("ghost", None, shed=True),  # shed: no weights
                _resp("noinc", None),  # no per-draw signal: skipped
            ]
        )
        assert n == 1
        lw = sched.weight_state_of("s")
        assert lw is not None and lw.shape == (4,)
        assert np.argmax(normalized_weights(lw)) == 0
        assert sched.weight_state_of("ghost") is None
        assert sched.weight_state_of("noinc") is None
        assert ladder.metrics.reweight_ticks == 1
        st = ladder.stanza()
        assert st["reweight_ticks"] == 1 and st["rejuvenations"] == 0
        assert st["ess"][0]["series"] == "s"

    def test_ess_floor_triggers_batched_rejuvenation(self):
        model, sched, _ = _attached_sched(n_draws=4)
        ladder = AdaptationLadder(
            sched, jax.random.PRNGKey(0), ess_floor_frac=0.9, forget=1.0
        )
        bank0 = np.asarray(sched.draw_bank_of("s"))
        gen0 = sched.attach_generation("s")
        # a brutal tilt: one draw dominates, ESS ~ 1 < floor 3.6
        inc = np.array([50.0, 0.0, 0.0, 0.0], np.float32)
        ladder.observe([_resp("s", inc)])
        assert ladder.metrics.rejuvenations == 1
        # the committed move: new bank (same shape/dtype), bumped
        # generation, uniform weights, ESS restored to D
        bank1 = np.asarray(sched.draw_bank_of("s"))
        assert bank1.shape == bank0.shape and bank1.dtype == bank0.dtype
        assert not np.array_equal(bank1, bank0)
        assert sched.attach_generation("s") == gen0 + 1
        np.testing.assert_allclose(
            sched.weight_state_of("s"), uniform_log_weights(4), rtol=1e-6
        )
        ev = ladder.stanza()["events"]
        assert ev and ev[-1]["kind"] == "rejuvenate"
        assert ev[-1]["reason"] == "ess_floor"
        assert ev[-1]["ess_after"] == 4.0 > ev[-1]["ess_before"]
        # ticking still serves after the swap-in (filter state intact)
        r = sched.tick({"s": {"x": 2}})["s"]
        assert not r.shed and not r.degraded

    def test_rejuvenation_budget_caps_per_flush(self):
        model = MultinomialHMM(K=2, L=3)
        sched = MicroBatchScheduler(model, buckets=(4,), history_tail=8)
        snap = _fake_snapshot(model, n_draws=4)
        sched.attach_many([(f"s{i}", snap, None) for i in range(3)])
        for t in range(2):
            sched.tick({f"s{i}": {"x": (t + i) % 3} for i in range(3)})
        ladder = AdaptationLadder(
            sched,
            jax.random.PRNGKey(1),
            ess_floor_frac=1.0,
            max_rejuv_per_flush=1,
        )
        inc = np.array([9.0, 0.0, 0.0, 0.0], np.float32)
        ladder.observe([_resp(f"s{i}", inc) for i in range(3)])
        assert ladder.metrics.rejuvenations == 1  # budget, not 3

    def test_plan_caps_feed_the_ladder(self):
        class FakePlan:
            def admission_caps(self):
                return {"ess_floor_frac": 0.25, "max_rejuv_per_flush": 3}

        model, sched, _ = _attached_sched()
        ladder = AdaptationLadder(
            sched, jax.random.PRNGKey(0), plan=FakePlan()
        )
        assert ladder.ess_floor_frac == 0.25
        assert ladder.max_rejuv_per_flush == 3
        assert ladder.ess_floor(8) == 2.0
        # explicit kwargs beat the plan
        l2 = AdaptationLadder(
            sched, jax.random.PRNGKey(0), plan=FakePlan(), ess_floor_frac=0.5
        )
        assert l2.ess_floor_frac == 0.5

    def test_constructor_validation(self):
        model, sched, _ = _attached_sched()
        with pytest.raises(ValueError, match="ess_floor_frac"):
            AdaptationLadder(
                sched, jax.random.PRNGKey(0), ess_floor_frac=0.0
            )
        with pytest.raises(ValueError, match="escalate_after"):
            AdaptationLadder(
                sched, jax.random.PRNGKey(0), escalate_after=0
            )

    def test_alarm_strikes_rejuvenate_then_escalate(self):
        model, sched, _ = _attached_sched(n_draws=4)
        ladder = AdaptationLadder(
            sched, jax.random.PRNGKey(0), escalate_after=2
        )
        assert ladder.on_alarm("s") == "rejuvenate"
        assert ladder.on_alarm("s") == "rejuvenate"
        assert ladder.metrics.rejuvenations == 2
        assert ladder.on_alarm("s") == "escalate"
        assert ladder.metrics.escalations == 1
        ev = ladder.stanza()["events"][-1]
        assert ev["kind"] == "escalate" and ev["strikes"] == 3
        # promotion clears the strikes: the ladder starts over
        ladder.on_promoted("s")
        assert ladder.on_alarm("s") == "rejuvenate"

    def test_rejuvenate_skips_unattached_and_unticked(self):
        model, sched, _ = _attached_sched()
        ladder = AdaptationLadder(sched, jax.random.PRNGKey(0))
        sched.attach("cold", _fake_snapshot(model, n_draws=4))
        assert ladder.rejuvenate(["nope", "cold"]) == 0
        assert ladder.metrics.rejuvenations == 0


class TestMaintRouting:
    """The loop.observe alarm path with a stub always-alarm detector
    and a recording fake ladder: fresh alarms are consumed by the
    ladder, escalations fall through to the refit queue, OWED alarms
    never re-enter the ladder."""

    class _AlwaysAlarm:
        def __init__(self):
            self.resets = 0

        def update(self, inc):
            return float(inc), True

        def reset(self):
            self.resets += 1

    class _FakeAdapt:
        def __init__(self, answers):
            self.answers = list(answers)
            self.calls = []

        def on_alarm(self, sid):
            self.calls.append(sid)
            return self.answers.pop(0)

        def on_promoted(self, sid):
            self.calls.append(("promoted", sid))

    def _loop(self, adapt, policy):
        from hhmm_tpu.infer import GibbsConfig
        from hhmm_tpu.maint import MaintenanceLoop

        model, sched, _ = _attached_sched(n_draws=4, history_tail=16)
        loop = MaintenanceLoop(
            sched,
            None,
            model,
            GibbsConfig(num_warmup=2, num_samples=2, num_chains=1),
            jax.random.PRNGKey(0),
            policy=policy,
            detector_factory=lambda sid: self._AlwaysAlarm(),
            adapt=adapt,
        )
        return model, sched, loop

    def _tick_and_observe(self, sched, loop, t):
        rs = sched.tick({"s": {"x": t % 3}})
        return loop.observe(rs.values())

    def test_fresh_alarm_consumed_by_ladder(self):
        from hhmm_tpu.maint import MaintenancePolicy

        fake = self._FakeAdapt(["rejuvenate", "rejuvenate", "escalate"])
        model, sched, loop = self._loop(
            fake, MaintenancePolicy(min_interval_ticks=1)
        )
        # tick 1 seeds the detector (no prev loglik -> no alarm)
        assert self._tick_and_observe(sched, loop, 0) == 0
        assert fake.calls == []
        # ticks 2-3: alarms answered by rejuvenation, nothing enqueued
        assert self._tick_and_observe(sched, loop, 1) == 0
        assert self._tick_and_observe(sched, loop, 2) == 0
        assert fake.calls == ["s", "s"]
        # tick 4: the ladder escalates -> the refit queue takes it
        assert self._tick_and_observe(sched, loop, 0) == 1
        assert fake.calls == ["s", "s", "s"]

    def test_owed_alarm_skips_the_ladder(self):
        from hhmm_tpu.maint import MaintenancePolicy

        # debounce window so large the second alarm cannot land
        fake = self._FakeAdapt(["escalate", "escalate", "escalate"])
        model, sched, loop = self._loop(
            fake, MaintenancePolicy(min_interval_ticks=1000)
        )
        self._tick_and_observe(sched, loop, 0)  # seed
        # first alarm: ladder escalates, policy accepts -> enqueued
        assert self._tick_and_observe(sched, loop, 1) == 1
        assert fake.calls == ["s"]
        # second alarm: ladder escalates, policy debounces -> OWED
        assert self._tick_and_observe(sched, loop, 2) == 0
        assert fake.calls == ["s", "s"]
        # third tick: the alarm is OWED — it must retry the policy
        # directly, NOT climb the ladder again (re-rejuvenating would
        # mask the signal the stuck refit is waiting on)
        assert self._tick_and_observe(sched, loop, 0) == 0
        assert fake.calls == ["s", "s"]

    def test_unwired_loop_routes_straight_to_policy(self):
        from hhmm_tpu.maint import MaintenancePolicy

        model, sched, loop = self._loop(
            None, MaintenancePolicy(min_interval_ticks=1)
        )
        self._tick_and_observe(sched, loop, 0)
        assert self._tick_and_observe(sched, loop, 1) == 1


class TestWeightStateLifecycle:
    """Satellite: the scheduler's opaque weight-state table across
    detach/page-in/swap/unregister — the contracts `adapt/` builds on."""

    def _paged(self, tmp_path, n_draws=3):
        from hhmm_tpu.serve import SnapshotPager

        model = MultinomialHMM(K=2, L=3)
        reg = SnapshotRegistry(str(tmp_path))
        reg.save("s", _fake_snapshot(model, n_draws=n_draws))
        pager = SnapshotPager(reg, budget_bytes=10**9)
        sched = MicroBatchScheduler(
            model, buckets=(4,), registry=reg, pager=pager, history_tail=16
        )
        return model, reg, pager, sched

    def test_weights_survive_detach_and_warm_page_in_bitwise(self, tmp_path):
        """Evict an adapted series, touch it back in: the replayed
        stream AND the weight state are bitwise the never-evicted
        ones — adaptation does not reset on paging churn."""
        model, reg, pager, sched = self._paged(tmp_path)
        control = MicroBatchScheduler(model, buckets=(4,), history_tail=16)
        control.attach("s", reg.load("s"))
        ladder = AdaptationLadder(sched, jax.random.PRNGKey(0))
        lctrl = AdaptationLadder(control, jax.random.PRNGKey(0))
        obs = [{"x": t % 3} for t in range(10)]
        for t in range(5):
            rp = sched.tick({"s": obs[t]})["s"]
            rc = control.tick({"s": obs[t]})["s"]
            assert not rp.shed and not rc.shed
            ladder.observe([rp])
            lctrl.observe([rc])
        w0 = np.asarray(sched.weight_state_of("s")).copy()
        assert pager.evict("s")  # detach: the weights SURVIVE
        assert "s" not in sched.series_ids()
        np.testing.assert_array_equal(sched.weight_state_of("s"), w0)
        for t in range(5, 10):
            rp = sched.tick({"s": obs[t]})["s"]  # t=5 pages in WARM
            rc = control.tick({"s": obs[t]})["s"]
            assert not rp.shed
            ladder.observe([rp])
            lctrl.observe([rc])
            np.testing.assert_array_equal(rp.probs, rc.probs)
            np.testing.assert_array_equal(
                rp.per_draw_loglik, rc.per_draw_loglik
            )
        wp = np.asarray(sched.weight_state_of("s"))
        wc = np.asarray(control.weight_state_of("s"))
        np.testing.assert_array_equal(wp, wc)
        assert wp.dtype == wc.dtype
        assert sched.metrics.warm_page_ins == 1

    def test_rejuvenated_bank_drops_weights_on_detach(self, tmp_path):
        """A rejuvenated bank lives only in memory: a page-in restores
        the ORIGINAL snapshot, so saved weights indexed against the
        rejuvenated cloud must not be replayed over it."""
        model, reg, pager, sched = self._paged(tmp_path, n_draws=4)
        for t in range(2):
            sched.tick({"s": {"x": t % 3}})
        ladder = AdaptationLadder(sched, jax.random.PRNGKey(0))
        assert ladder.rejuvenate(["s"]) == 1
        sched.set_weight_state(
            "s", np.array([0.0, -1.0, -2.0, -3.0], np.float32)
        )
        assert pager.evict("s")
        assert sched.weight_state_of("s") is None

    def test_swap_snapshot_resets_weights(self, tmp_path):
        model, reg, pager, sched = self._paged(tmp_path)
        sched.tick({"s": {"x": 0}})
        sched.set_weight_state("s", uniform_log_weights(3) + 0.5)
        reg.promote("s", _fake_snapshot(model, n_draws=3, seed=9))
        assert sched.swap_snapshot("s") is None
        # the committed attach reset the stored state: new draws,
        # uniform (= no stored) weights
        assert sched.weight_state_of("s") is None

    def test_unregister_releases_weights(self, tmp_path):
        model, reg, pager, sched = self._paged(tmp_path)
        sched.tick({"s": {"x": 0}})
        sched.set_weight_state("s", uniform_log_weights(3))
        assert sched.unregister("s")
        assert sched.weight_state_of("s") is None

    def test_shed_ticks_carry_no_increment(self):
        """The reweighting signal is absent exactly when nothing was
        folded: a shed response has per_draw_loglik=None, and the
        ladder leaves the weight table untouched."""
        model = MultinomialHMM(K=2, L=3)
        sched = MicroBatchScheduler(model, buckets=(4,))
        r = sched.tick({"nobody": {"x": 0}})["nobody"]  # no registry
        assert r.shed and r.per_draw_loglik is None and r.draw_ok is None
        ladder = AdaptationLadder(sched, jax.random.PRNGKey(0))
        assert ladder.observe([r]) == 0
        assert sched.weight_state_of("nobody") is None

    def test_replace_draw_bank_validation(self):
        model, sched, _ = _attached_sched(n_draws=4)
        bank = np.asarray(sched.draw_bank_of("s"))
        alpha, ll, ok = sched.filter_state_of("s")
        err = sched.replace_draw_bank("ghost", bank, alpha, ll, ok)
        assert "not attached" in err
        sched.attach("cold", _fake_snapshot(model, n_draws=4))
        err = sched.replace_draw_bank("cold", bank, alpha, ll, ok)
        assert "not received a tick" in err
        # fixed-D contract: draw-count and dtype must match exactly
        err = sched.replace_draw_bank("s", bank[:2], alpha, ll, ok)
        assert "fixed-D" in err
        # float16 survives jnp.asarray (float64 would silently demote
        # back to float32 without x64, masking the mismatch)
        err = sched.replace_draw_bank(
            "s", bank.astype(np.float16), alpha, ll, ok
        )
        assert "fixed-D" in err
        err = sched.replace_draw_bank("s", bank, alpha[:2], ll, ok)
        assert "filter state shape" in err
        # a refused replacement left the serving state untouched
        np.testing.assert_array_equal(
            np.asarray(sched.draw_bank_of("s")), bank
        )


class TestWeightedForecast:
    """Satellite: `posterior_predictive_mean` weights are a measure,
    not a mask — fractional values tilt the mixture."""

    def _inputs(self):
        # uniform filters/transitions: the predictive state dist is
        # uniform, so each draw's forecast is the mean of its mu row
        d, k = 3, 2
        la = np.full((d, k), np.log(0.5), np.float32)
        lA = np.full((d, k, k), np.log(0.5), np.float32)
        mu = np.array([[0.0, 0.0], [1.0, 1.0], [2.0, 2.0]], np.float32)
        return la, lA, mu  # per-draw forecasts: [0, 1, 2]

    def test_fractional_weights_honored_not_binarized(self):
        la, lA, mu = self._inputs()
        w = np.array([0.5, 0.25, 0.25], np.float32)
        got = float(posterior_predictive_mean(la, lA, mu, weights=w))
        # binarizing w into a mask would give mean([0,1,2]) = 1.0
        np.testing.assert_allclose(got, 0.75, rtol=1e-6)
        # and the adaptation plane's exp-weights plug straight in
        lw = np.log(np.array([0.5, 0.25, 0.25], np.float32))
        got2 = float(
            posterior_predictive_mean(
                la, lA, mu, weights=normalized_weights(lw)
            )
        )
        np.testing.assert_allclose(got2, 0.75, rtol=1e-6)

    def test_nonfinite_weights_and_draws_zeroed(self):
        la, lA, mu = self._inputs()
        # NaN/negative weights contribute nothing (not NaN-poisoning)
        w = np.array([1.0, np.nan, -2.0], np.float32)
        got = float(posterior_predictive_mean(la, lA, mu, weights=w))
        np.testing.assert_allclose(got, 0.0, atol=1e-7)
        # a weighted draw whose own forecast is NaN sheds its mass
        mu2 = mu.copy()
        mu2[0] = np.nan
        w2 = np.array([1.0, 1.0, 0.0], np.float32)
        got2 = float(posterior_predictive_mean(la, lA, mu2, weights=w2))
        np.testing.assert_allclose(got2, 1.0, rtol=1e-6)

    def test_zero_mass_falls_back_to_finite_draws(self):
        la, lA, mu = self._inputs()
        w = np.zeros(3, np.float32)
        got = float(posterior_predictive_mean(la, lA, mu, weights=w))
        np.testing.assert_allclose(got, 1.0, rtol=1e-6)  # mean of 0,1,2
        # only a series with NO finite per-draw value yields NaN
        mu_nan = np.full_like(mu, np.nan)
        assert np.isnan(
            float(posterior_predictive_mean(la, lA, mu_nan, weights=w))
        )
        # unweighted path unchanged: plain draw mean
        np.testing.assert_allclose(
            float(posterior_predictive_mean(la, lA, mu)), 1.0, rtol=1e-6
        )


class TestCompileDiscipline:
    def test_rejuvenation_lands_on_bucket_shapes(self):
        """Two single-series rejuvenations after a warm one add no jit
        signatures: the ladder pads to the scheduler's bucket ladder,
        so the move only ever compiles per bucket shape."""
        model = MultinomialHMM(K=2, L=3)
        sched = MicroBatchScheduler(model, buckets=(4,), history_tail=8)
        snap = _fake_snapshot(model, n_draws=4)
        sched.attach_many([(f"s{i}", snap, None) for i in range(3)])
        for t in range(2):
            sched.tick({f"s{i}": {"x": (t + i) % 3} for i in range(3)})
        ladder = AdaptationLadder(sched, jax.random.PRNGKey(0))
        assert ladder.rejuvenate(["s0"]) == 1  # warms the [4,...] shape
        warm = ladder.rejuvenator.compile_count
        assert warm >= 1
        assert ladder.rejuvenate(["s1"]) == 1
        assert ladder.rejuvenate(["s0", "s1", "s2"]) == 3  # padded to 4
        assert ladder.rejuvenator.compile_count == warm

    def test_tick_after_rejuvenation_compile_flat(self):
        model, sched, _ = _attached_sched(n_draws=4)
        sched.tick({"s": {"x": 2}})
        warm = sched.metrics.compile_count
        ladder = AdaptationLadder(sched, jax.random.PRNGKey(0))
        assert ladder.rejuvenate(["s"]) == 1
        r = sched.tick({"s": {"x": 1}})["s"]
        assert not r.shed and not r.degraded
        assert sched.metrics.compile_count == warm


# ---------------------------------------------------------------------------
# the end-to-end closed-loop gate (subprocess, slow)


@pytest.mark.slow
class TestAdaptBenchQuick:
    def test_adapt_quick_tracks_the_shift(self):
        """`bench.py --adapt --quick` exits 0 only if the weighted arm
        beats the uniform-stale arm post-shift (paired AND pooled),
        every rejuvenation restored ESS above the floor, the adaptive
        arm refit strictly less than the refit-only baseline, and zero
        compiles landed after warmup."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--adapt", "--quick", "--cpu"],
            capture_output=True,
            text=True,
            env=env,
            timeout=560,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        rec = json.loads(proc.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "tayal_adapt_tick_throughput"
        adapt = rec["manifest"]["adapt"]
        assert adapt["tracking_advantage"] is True
        assert adapt["paired_mean_delta"] > 0
        assert adapt["pooled_mean_delta"] > 0
        assert adapt["reweight_ticks"] > 0
        assert adapt["rejuvenations"] >= 1
        assert adapt["refits_adaptive"] < adapt["refits_baseline"]
        assert rec["compiles_after_warmup"] == 0
        assert "CLOSED-LOOP OK" in proc.stderr
