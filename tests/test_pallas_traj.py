"""Fused Tayal trajectory kernel (`kernels/pallas_traj.py`) parity
tests: the kernel's in-kernel bijectors, gating, Baum-Welch chain rule,
and leapfrog algebra must reproduce the unfused reference path —
`infer/chees.py::leapfrogs` over `TayalHHMM().make_vg` — exactly
(f32 tolerances), in interpreter mode on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute suites; fast subset: -m 'not slow'

from __graft_entry__ import _tayal_batch
from hhmm_tpu.kernels.dispatch import make_tayal_trajectory, tayal_trajectory
from hhmm_tpu.models import TayalHHMM


def _reference_trajectory(model, data, inv_mass, eps, n_steps, q, p, grad):
    """The unfused leapfrog loop of `infer/chees.py::leapfrogs` with the
    per-series fused value+grad (series x chains batch)."""
    B, C, D = q.shape

    def lp_one(xi, si, qi):
        return model.make_vg({"x": xi, "sign": si})(qi)

    def lp_bc(qs):
        lps, grads = jax.vmap(
            lambda xi, si, qc: jax.vmap(lambda qq: lp_one(xi, si, qq))(qc)
        )(data["x"], data["sign"], qs)
        return lps, grads

    logp, g = lp_bc(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(grad), rtol=1e-4, atol=1e-4)
    for _ in range(int(n_steps)):
        p_half = p + 0.5 * eps * g
        q = q + eps * inv_mass[:, None, :] * p_half
        logp, g = lp_bc(q)
        p = p_half + 0.5 * eps * g
    return q, p, logp, g


class TestTrajectoryParity:
    @pytest.mark.parametrize("n_steps", [1, 3, 8])
    def test_matches_unfused_leapfrogs(self, n_steps):
        B, C, T, D = 3, 2, 64, 35
        model = TayalHHMM()  # stan gate — the bench ChEES model
        x, sign = _tayal_batch(B, T, seed=5)
        data = {"x": x, "sign": sign}
        key = jax.random.PRNGKey(0)
        q = jnp.stack(
            [
                jnp.stack(
                    [
                        model.init_unconstrained(
                            jax.random.fold_in(key, b * 10 + c),
                            {"x": x[b], "sign": sign[b]},
                        )
                        for c in range(C)
                    ]
                )
                for b in range(B)
            ]
        )  # [B, C, D]
        p = 0.7 * jax.random.normal(jax.random.fold_in(key, 99), (B, C, D))
        inv_mass = jnp.exp(
            0.3 * jax.random.normal(jax.random.fold_in(key, 98), (B, D))
        )
        eps = jnp.asarray(0.02, jnp.float32)

        # gradient at the start point (what the sampler carries)
        def vg_flat(qf, xb, sb):
            return model.make_vg({"x": xb, "sign": sb})(qf)

        g0 = jnp.stack(
            [
                jnp.stack([vg_flat(q[b, c], x[b], sign[b])[1] for c in range(C)])
                for b in range(B)
            ]
        )

        traj = make_tayal_trajectory(data, cap=8, interpret=True)
        q1, p1, lp1, g1 = traj(
            inv_mass, eps, jnp.asarray(n_steps, jnp.int32), q, p, None, g0
        )
        qr, pr, lpr, gr = _reference_trajectory(
            model, data, inv_mass, float(eps), n_steps, q, p, g0
        )
        np.testing.assert_allclose(np.asarray(q1), np.asarray(qr), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(p1), np.asarray(pr), rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(lp1), np.asarray(lpr), rtol=1e-4, atol=5e-3)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(gr), rtol=2e-3, atol=2e-3)

    def test_chees_with_fused_trajectory_samples_same_posterior(self):
        """End-to-end: `sample_chees_batched` with the fused trajectory
        targets the same posterior as the unfused path (f32 rounding
        diverges individual chains chaotically, so the gate is
        statistical: posterior means within MC error, no divergences)."""
        from hhmm_tpu.infer import ChEESConfig, make_lp_bc, sample_chees_batched
        from hhmm_tpu.batch import default_init

        B, C, T = 4, 2, 96
        model = TayalHHMM()
        x, sign = _tayal_batch(B, T, seed=11)
        data = {"x": x, "sign": sign}
        cfg = ChEESConfig(num_warmup=120, num_samples=150, num_chains=C, max_leapfrogs=8)
        init = default_init(model, data, B, C, jax.random.PRNGKey(3))
        lp_bc = make_lp_bc(model, data)
        probe = model.make_vg({"x": x[0], "sign": sign[0]})
        qs_u, st_u = sample_chees_batched(
            lp_bc, jax.random.PRNGKey(4), init, cfg, probe_vg=probe
        )
        traj = make_tayal_trajectory(data, cap=cfg.max_leapfrogs, interpret=True)
        qs_f, st_f = sample_chees_batched(
            lp_bc, jax.random.PRNGKey(4), init, cfg, probe_vg=probe,
            trajectory_fn=traj,
        )
        assert not bool(np.asarray(st_f["diverging"]).any())
        m_u = np.asarray(qs_u).reshape(B, -1, qs_u.shape[-1]).mean(axis=1)
        m_f = np.asarray(qs_f).reshape(B, -1, qs_f.shape[-1]).mean(axis=1)
        sd = np.asarray(qs_u).reshape(B, -1, qs_u.shape[-1]).std(axis=1)
        np.testing.assert_array_less(
            np.abs(m_u - m_f), 5.0 * sd / np.sqrt(20.0) + 0.25
        )

    def test_masked_padding_matches_truncated(self):
        B, C, T = 2, 2, 48
        model = TayalHHMM()
        x, sign = _tayal_batch(B, T, seed=9)
        Tv = 32
        mask = np.zeros((B, T), np.float32)
        mask[:, :Tv] = 1.0
        key = jax.random.PRNGKey(1)
        q = jnp.stack(
            [
                jnp.stack(
                    [
                        model.init_unconstrained(
                            jax.random.fold_in(key, b * 7 + c),
                            {"x": x[b, :Tv], "sign": sign[b, :Tv]},
                        )
                        for c in range(C)
                    ]
                )
                for b in range(B)
            ]
        )
        p = 0.5 * jax.random.normal(jax.random.fold_in(key, 5), q.shape)
        im = jnp.ones((B, q.shape[-1]))
        eps = jnp.asarray(0.03, jnp.float32)

        def g_of(data_b, qf):
            return model.make_vg(data_b)(qf)[1]

        g0 = jnp.stack(
            [
                jnp.stack([g_of({"x": x[b, :Tv], "sign": sign[b, :Tv]}, q[b, c]) for c in range(2)])
                for b in range(B)
            ]
        )
        full = make_tayal_trajectory(
            {"x": x, "sign": sign, "mask": mask}, cap=4, interpret=True
        )
        trunc = make_tayal_trajectory(
            {"x": x[:, :Tv], "sign": sign[:, :Tv]}, cap=4, interpret=True
        )
        n = jnp.asarray(3, jnp.int32)
        out_f = full(im, eps, n, q, p, None, g0)
        out_t = trunc(im, eps, n, q, p, None, g0)
        for a, b_ in zip(out_f, out_t):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-4
            )
