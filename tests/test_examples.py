"""Example driver scripts (examples/) — compile-check all, run one end
to end (the rest exercise the same library surface already covered by
the app tests; a full subprocess run of each would dominate suite
time)."""

import os
import py_compile
import subprocess
import sys

import pytest

_EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def test_all_examples_compile():
    scripts = [f for f in os.listdir(_EXAMPLES) if f.endswith(".py")]
    assert len(scripts) >= 7
    for f in scripts:
        py_compile.compile(os.path.join(_EXAMPLES, f), doraise=True)


def test_hmm_main_quick_runs():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, "hmm_main.py"), "--cpu", "--quick", "--T", "300"],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "filtered accuracy" in out.stdout
    assert "divergence rate" in out.stdout
