"""Example driver scripts (examples/) — compile-check all, run one end
to end (the rest exercise the same library surface already covered by
the app tests; a full subprocess run of each would dominate suite
time)."""

import os
import py_compile
import subprocess
import sys

import pytest

_EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def test_all_examples_compile():
    scripts = [f for f in os.listdir(_EXAMPLES) if f.endswith(".py")]
    assert len(scripts) >= 7
    for f in scripts:
        py_compile.compile(os.path.join(_EXAMPLES, f), doraise=True)


@pytest.mark.slow
def test_hmm_main_quick_runs():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, "hmm_main.py"), "--cpu", "--quick", "--T", "300"],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "filtered accuracy" in out.stdout
    assert "divergence rate" in out.stdout


def test_replication_figures_appendix(tmp_path):
    """The per-stock appendix generator (`tayal2009/Rmd/appendix-wf.Rmd`
    analog) renders tables + equity figures from the committed wf
    artifact without touching a device."""
    import json

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    sys.path.insert(0, _EXAMPLES)
    try:
        import replication_figures as rf
    finally:
        sys.path.remove(_EXAMPLES)

    root = os.path.dirname(_EXAMPLES)
    with open(os.path.join(root, "results", "tayal_replication.json")) as f:
        rep = json.load(f)
    os.makedirs(tmp_path / "docs" / "figures", exist_ok=True)
    old_out, old_root = rf.OUT, rf.ROOT
    rf.OUT, rf.ROOT = str(tmp_path / "docs" / "figures"), str(tmp_path)
    try:
        rf.appendix(rep, plt)
    finally:
        rf.OUT, rf.ROOT = old_out, old_root
    apx = (tmp_path / "docs" / "appendix-wf.md").read_text()
    symbols = {r["symbol"] for r in rep["wf"]["per_window"]}
    for sym in symbols:
        assert f"## {sym}" in apx
        assert (tmp_path / "docs" / "figures" / f"appendix_equity_{sym}.png").exists()
    assert "| **Total %** |" in apx


@pytest.mark.slow
def test_bench_quick_cpu_runs():
    """`bench.py --quick --cpu` end-to-end: the driver-facing benchmark
    must keep emitting its one-line JSON schema (incl. the round-3
    roofline fields) without a device."""
    import json

    root = os.path.dirname(_EXAMPLES)
    out = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"), "--quick", "--cpu"],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=root,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["metric"] == "tayal_batched_posterior_throughput"
    assert line["unit"] == "series/sec"
    for field in ("vs_baseline", "achieved_gflops", "hbm_gbps", "peak_fraction"):
        assert field in line


@pytest.mark.slow
@pytest.mark.parametrize("driver,args", [
    ("hmm_main.py", ["--variant", "multinom", "--T", "250"]),
    ("hmm_main.py", ["--variant", "semisup", "--T", "250"]),
    ("iohmm_main.py", ["--variant", "reg", "--T", "200"]),
])
def test_driver_variants_run(driver, args):
    """Run-through (not just compile-check) of the remaining reference
    driver variants (`hmm/main-multinom*.R`, `iohmm-reg/main.R`)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, driver), "--cpu", "--quick", *args],
        capture_output=True,
        text=True,
        timeout=540,
        env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "divergence rate" in out.stdout
