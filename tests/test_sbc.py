"""Simulation-based calibration (Cook, Gelman & Rubin 2006) as a test
suite — the reference's core verification discipline (SURVEY.md §4.1)
promoted to an automated check.

For models whose priors are proper (flat Dirichlet/uniform on the
constrained space), draw theta ~ prior, simulate data | theta, fit the
posterior, and rank theta among (thinned) posterior draws: over
replications the ranks must be uniform. All replications run as ONE
batched NUTS program (`fit_batched`), so the suite doubles as an
integration test of the batch engine on heterogeneous simulated data.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import kstest

from hhmm_tpu.batch import fit_batched
from hhmm_tpu.infer import SamplerConfig
from hhmm_tpu.models import MultinomialHMM, TayalHHMM
from hhmm_tpu.models.tayal import _UP_STATES, UP
from hhmm_tpu.sim import hmm_sim, obsmodel_categorical

N_REPS = 12
THIN = 4


def _ranks(theta_true: np.ndarray, draws: np.ndarray) -> np.ndarray:
    """Rank of each true scalar among its thinned posterior draws,
    normalized to (0, 1). ``theta_true`` [P], ``draws`` [S, P]."""
    thinned = draws[::THIN]
    r = (thinned < theta_true[None, :]).sum(axis=0)
    return (r + 0.5) / (thinned.shape[0] + 1)


def _uniformity_ok(u: np.ndarray) -> None:
    # loose gates: tiny-budget MCMC ranks are noisy; catastrophic
    # miscalibration (systematic bias, over/under-dispersion) still fails
    assert 0.30 < u.mean() < 0.70, f"rank mean {u.mean():.3f}"
    p = kstest(u, "uniform").pvalue
    assert p > 1e-3, f"KS uniformity p={p:.2e}"


class TestSBCTayal:
    def test_rank_uniformity(self, rng):
        """Tayal sparse HMM, hard gating: priors are uniform on (0,1) /
        the simplex, so prior draws + `hmm_sim` from the assembled
        sparse (pi, A) give exact joint samples."""
        model = TayalHHMM(gate_mode="hard")
        datasets, trues = [], []
        for _ in range(N_REPS):
            p11 = rng.uniform()
            A_row = rng.dirichlet(np.ones(2), size=2)
            phi = rng.dirichlet(np.ones(9), size=4)
            params = {
                "p_11": jnp.asarray(p11),
                "A_row": jnp.asarray(A_row),
                "phi_k": jnp.asarray(phi),
            }
            pi, A = model.assemble(params)
            z, x = hmm_sim(
                jax.random.PRNGKey(int(rng.integers(1 << 30))),
                300,
                np.asarray(A),
                np.asarray(pi),
                obsmodel_categorical(phi),
                validate=False,
            )
            sign = np.where(_UP_STATES[np.asarray(z)], UP, 1 - UP)
            datasets.append(
                {
                    "x": np.asarray(x, dtype=np.int32),
                    "sign": sign.astype(np.int32),
                    "mask": np.ones(300, np.float32),
                }
            )
            trues.append(
                np.concatenate([[p11], [A_row[0, 0], A_row[1, 0]], phi[:, 0], [phi[2, 4]]])
            )
        data = {
            k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]
        }
        # max_treedepth=5 matches the benchmark default (bench.py): this
        # suite is the calibration evidence for that trajectory budget
        cfg = SamplerConfig(
            num_warmup=150, num_samples=200, num_chains=1, max_treedepth=5
        )
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(0), cfg, chunk_size=N_REPS)
        assert float(np.asarray(stats["diverging"]).mean()) < 0.1

        units = []
        for i in range(N_REPS):
            draws = model.constrained_draws(qs[i])
            flat = np.column_stack(
                [
                    np.asarray(draws["p_11"]).reshape(-1),
                    np.asarray(draws["A_row"]).reshape(-1, 4)[:, 0],
                    np.asarray(draws["A_row"]).reshape(-1, 4)[:, 2],
                    *[np.asarray(draws["phi_k"]).reshape(-1, 4, 9)[:, k, 0] for k in range(4)],
                    np.asarray(draws["phi_k"]).reshape(-1, 4, 9)[:, 2, 4],
                ]
            )
            units.append(_ranks(trues[i], flat))
        _uniformity_ok(np.concatenate(units))


class TestSBCMultinomial:
    def test_rank_uniformity(self, rng):
        K, L, T = 2, 3, 250
        model = MultinomialHMM(K=K, L=L)
        datasets, trues = [], []
        for _ in range(N_REPS):
            p1 = rng.dirichlet(np.ones(K))
            A = rng.dirichlet(np.ones(K), size=K)
            phi = rng.dirichlet(np.ones(L), size=K)
            z, x = hmm_sim(
                jax.random.PRNGKey(int(rng.integers(1 << 30))),
                T,
                A,
                p1,
                obsmodel_categorical(phi),
                validate=False,
            )
            datasets.append(
                {"x": np.asarray(x, dtype=np.int32), "mask": np.ones(T, np.float32)}
            )
            trues.append(np.concatenate([[p1[0]], [A[0, 0], A[1, 1]], phi[:, 0]]))
        data = {
            k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]
        }
        # max_treedepth=5 matches the benchmark default (bench.py): this
        # suite is the calibration evidence for that trajectory budget
        cfg = SamplerConfig(
            num_warmup=150, num_samples=200, num_chains=1, max_treedepth=5
        )
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(1), cfg, chunk_size=N_REPS)
        assert float(np.asarray(stats["diverging"]).mean()) < 0.1

        # label switching: the multinomial posterior is invariant under
        # state permutation; canonicalize each draw by sorting states on
        # phi[:, 0] and canonicalize the truth identically
        units = []
        for i in range(N_REPS):
            draws = model.constrained_draws(qs[i])
            p1d = np.asarray(draws["p_1k"]).reshape(-1, K)
            Ad = np.asarray(draws["A_ij"]).reshape(-1, K, K)
            phid = np.asarray(draws["phi_k"]).reshape(-1, K, L)
            order = np.argsort(phid[:, :, 0], axis=1)  # [S, K]
            s_idx = np.arange(p1d.shape[0])[:, None]
            p1d = np.take_along_axis(p1d, order, axis=1)
            phid = phid[s_idx, order]
            Ad = Ad[s_idx[:, :, None], order[:, :, None], order[:, None, :]]
            # canonical truth from the stored raw values
            raw_p1 = np.array([trues[i][0], 1 - trues[i][0]])
            raw_A = np.array(
                [
                    [trues[i][1], 1 - trues[i][1]],
                    [1 - trues[i][2], trues[i][2]],
                ]
            )
            raw_phi0 = trues[i][3:5]
            torder = np.argsort(raw_phi0)
            flat = np.column_stack(
                [
                    p1d[:, 0],
                    Ad[:, 0, 0],
                    Ad[:, 1, 1],
                    phid[:, 0, 0],
                    phid[:, 1, 0],
                ]
            )
            truth = np.array(
                [
                    raw_p1[torder][0],
                    raw_A[torder][:, torder][0, 0],
                    raw_A[torder][:, torder][1, 1],
                    raw_phi0[torder][0],
                    raw_phi0[torder][1],
                ]
            )
            units.append(_ranks(truth, flat))
        _uniformity_ok(np.concatenate(units))
