"""Simulation-based calibration (Cook, Gelman & Rubin 2006) as a test
suite — the reference's core verification discipline (SURVEY.md §4.1)
promoted to an automated check.

For models whose priors are proper (flat Dirichlet/uniform on the
constrained space), draw theta ~ prior, simulate data | theta, fit the
posterior, and rank theta among (thinned) posterior draws: over
replications the ranks must be uniform. All replications run as ONE
batched NUTS program (`fit_batched`), so the suite doubles as an
integration test of the batch engine on heterogeneous simulated data.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute suites; fast subset: -m 'not slow'
from scipy.stats import kstest

from scipy.stats import truncnorm

from hhmm_tpu.batch import fit_batched
from hhmm_tpu.infer import GibbsConfig, SamplerConfig
from hhmm_tpu.models import (
    GaussianHMM,
    IOHMMHMix,
    IOHMMReg,
    MultinomialHMM,
    NIGPrior,
    TayalHHMM,
    TreeHMM,
)
from hhmm_tpu.models.tayal import _UP_STATES, UP
from hhmm_tpu.sim import hmm_sim, obsmodel_categorical, obsmodel_gaussian

N_REPS = 24
THIN = 4


def _ranks(theta_true: np.ndarray, draws: np.ndarray, thin: int = THIN) -> np.ndarray:
    """Rank of each true scalar among its thinned posterior draws,
    normalized to (0, 1). ``theta_true`` [P], ``draws`` [S, P]."""
    thinned = draws[::thin]
    r = (thinned < theta_true[None, :]).sum(axis=0)
    return (r + 0.5) / (thinned.shape[0] + 1)


def _uniformity_ok(u: np.ndarray) -> None:
    """Loose gates: tiny-budget MCMC ranks are noisy; catastrophic
    miscalibration (systematic bias, over/under-dispersion) still fails.

    1-D input: pooled KS (legacy form). 2-D [reps, quantities]: KS per
    quantity column — ranks of the SAME rep are posterior-correlated, so
    pooling them violates the KS iid assumption and over-rejects; each
    column is iid across independent replications."""
    u = np.asarray(u)
    assert 0.30 < u.mean() < 0.70, f"rank mean {u.mean():.3f}"
    cols = [u] if u.ndim == 1 else list(u.T)
    ps = np.array([kstest(c, "uniform").pvalue for c in cols])
    assert ps.min() > 1e-3, f"KS uniformity min p={ps.min():.2e} (per-col {ps.round(4)})"


class TestSBCTayal:
    def test_rank_uniformity(self, rng):
        """Tayal sparse HMM, hard gating: priors are uniform on (0,1) /
        the simplex, so prior draws + `hmm_sim` from the assembled
        sparse (pi, A) give exact joint samples."""
        model = TayalHHMM(gate_mode="hard")
        datasets, trues = [], []
        for _ in range(N_REPS):
            p11 = rng.uniform()
            A_row = rng.dirichlet(np.ones(2), size=2)
            phi = rng.dirichlet(np.ones(9), size=4)
            params = {
                "p_11": jnp.asarray(p11),
                "A_row": jnp.asarray(A_row),
                "phi_k": jnp.asarray(phi),
            }
            pi, A = model.assemble(params)
            z, x = hmm_sim(
                jax.random.PRNGKey(int(rng.integers(1 << 30))),
                300,
                np.asarray(A),
                np.asarray(pi),
                obsmodel_categorical(phi),
                validate=False,
            )
            sign = np.where(_UP_STATES[np.asarray(z)], UP, 1 - UP)
            datasets.append(
                {
                    "x": np.asarray(x, dtype=np.int32),
                    "sign": sign.astype(np.int32),
                    "mask": np.ones(300, np.float32),
                }
            )
            trues.append(
                np.concatenate([[p11], [A_row[0, 0], A_row[1, 0]], phi[:, 0], [phi[2, 4]]])
            )
        data = {
            k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]
        }
        # max_treedepth=5 matches the benchmark default (bench.py): this
        # suite is the calibration evidence for that trajectory budget
        cfg = SamplerConfig(
            num_warmup=150, num_samples=200, num_chains=1, max_treedepth=5
        )
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(0), cfg, chunk_size=N_REPS)
        assert float(np.asarray(stats["diverging"]).mean()) < 0.1

        units = []
        for i in range(N_REPS):
            draws = model.constrained_draws(qs[i])
            flat = np.column_stack(
                [
                    np.asarray(draws["p_11"]).reshape(-1),
                    np.asarray(draws["A_row"]).reshape(-1, 4)[:, 0],
                    np.asarray(draws["A_row"]).reshape(-1, 4)[:, 2],
                    *[np.asarray(draws["phi_k"]).reshape(-1, 4, 9)[:, k, 0] for k in range(4)],
                    np.asarray(draws["phi_k"]).reshape(-1, 4, 9)[:, 2, 4],
                ]
            )
            units.append(_ranks(trues[i], flat))
        _uniformity_ok(np.stack(units))


class TestSBCGaussianGibbs:
    def test_rank_uniformity(self, rng):
        """Gaussian HMM with the NIG emission prior, fitted by the
        blocked Gibbs sampler (`infer/gibbs.py`) — the calibration
        evidence for the FFBS + joint-NIG + ordered-cone-accept
        transition. Prior draws: Dirichlet(1) simplexes; sorted iid NIG
        emissions (= the exact ordered-cone prior)."""
        K, T = 2, 250
        prior = NIGPrior(m0=0.0, kappa0=0.5, a0=3.0, b0=1.5)
        model = GaussianHMM(K=K, nig_prior=prior)
        datasets, trues = [], []
        for r in range(N_REPS):
            p1 = rng.dirichlet(np.ones(K))
            A = rng.dirichlet(np.ones(K), size=K)
            v = 1.0 / rng.gamma(prior.a0, 1.0 / prior.b0, size=K)
            sigma = np.sqrt(v)
            mu = prior.m0 + sigma / np.sqrt(prior.kappa0) * rng.standard_normal(K)
            order = np.argsort(mu)
            mu, sigma = mu[order], sigma[order]
            z, x = hmm_sim(
                jax.random.PRNGKey(int(rng.integers(1 << 30))),
                T,
                A,
                p1,
                obsmodel_gaussian(mu, sigma),
                validate=False,
            )
            datasets.append(
                {"x": np.asarray(x, np.float32), "mask": np.ones(T, np.float32)}
            )
            trues.append(
                np.concatenate([mu, sigma, [A[0, 0], A[1, 1]], [p1[0]]])
            )
        data = {
            k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]
        }
        cfg = GibbsConfig(num_warmup=150, num_samples=400, num_chains=1)
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(2), cfg, chunk_size=N_REPS)
        assert np.isfinite(np.asarray(stats["logp"])).all()

        units = []
        for i in range(N_REPS):
            draws = model.constrained_draws(qs[i])
            flat = np.column_stack(
                [
                    np.asarray(draws["mu_k"]).reshape(-1, K),
                    np.asarray(draws["sigma_k"]).reshape(-1, K),
                    np.asarray(draws["A_ij"]).reshape(-1, K, K)[:, [0, 1], [0, 1]],
                    np.asarray(draws["p_1k"]).reshape(-1, K)[:, :1],
                ]
            )
            units.append(_ranks(trues[i], flat))
        _uniformity_ok(np.stack(units))


class TestSBCIOHMMReg:
    def test_rank_uniformity(self, rng):
        """IOHMM-reg (`iohmm-reg/stan/iohmm-reg.stan` semantics): proper
        priors w,b ~ N(0,5), s ~ half-N(0,3) (`:113-121`). States are
        exchangeable — both truth and draws are canonicalized by sorting
        states on b[k, 0] (a measurable function, so SBC stays exact).

        Simulation matches the model's factorization exactly: z_1 ~
        p_1k, z_t ~ softmax(u_t w) for t >= 2 (the rank-1 "stan"
        transition convention, SURVEY.md §2.8 item 2)."""
        K, M, T = 2, 2, 220
        model = IOHMMReg(K=K, M=M)
        datasets, trues = [], []
        for r in range(N_REPS):
            u = np.column_stack([np.ones(T), rng.standard_normal(T)]).astype(np.float32)
            p1 = rng.dirichlet(np.ones(K))
            w = 5.0 * rng.standard_normal((K, M))
            b = 5.0 * rng.standard_normal((K, M))
            s = np.abs(3.0 * rng.standard_normal(K)) + 1e-3
            probs = np.exp(u @ w.T)
            probs /= probs.sum(axis=1, keepdims=True)
            z = np.empty(T, np.int64)
            z[0] = rng.choice(K, p=p1)
            for t in range(1, T):
                z[t] = rng.choice(K, p=probs[t])
            x = (u * b[z]).sum(axis=1) + s[z] * rng.standard_normal(T)
            datasets.append(
                {
                    "x": x.astype(np.float32),
                    "u": u,
                    "mask": np.ones(T, np.float32),
                }
            )
            o = np.argsort(b[:, 0])
            trues.append(
                np.concatenate([b[o].ravel(), s[o], w[o][:, 1]])
            )
        data = {
            k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]
        }
        # wide reference priors (N(0,5)) make some replications genuinely
        # hard at tiny budgets; 250w/300s keeps the pooled ranks clean
        cfg = SamplerConfig(num_warmup=250, num_samples=300, num_chains=1, max_treedepth=5)
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(3), cfg, chunk_size=N_REPS)
        assert float(np.asarray(stats["diverging"]).mean()) < 0.1

        units = []
        for i in range(N_REPS):
            draws = model.constrained_draws(qs[i])
            bd = np.asarray(draws["b_km"]).reshape(-1, K, M)
            sd = np.asarray(draws["s_k"]).reshape(-1, K)
            wd = np.asarray(draws["w_km"]).reshape(-1, K, M)
            o = np.argsort(bd[:, :, 0], axis=1)
            idx = np.arange(len(bd))[:, None]
            flat = np.column_stack(
                [
                    bd[idx, o].reshape(len(bd), -1),
                    np.take_along_axis(sd, o, axis=1),
                    wd[idx, o][:, :, 1],
                ]
            )
            units.append(_ranks(trues[i], flat, thin=6))
        _uniformity_ok(np.stack(units))


class TestSBCIOHMMHMix:
    def test_rank_uniformity(self, rng):
        """Hierarchical IOHMM mixture (`iohmm-mix/stan/iohmm-hmix.stan`):
        ordered hypermu identifies states, ordered mu_kl identifies
        components, so no canonicalization is needed. L=2 with h5 = h6
        makes the reference's per-component Beta factor on the simplex
        row reduce to an exactly samplable Beta(h5+h6-1, h5+h6-1) on
        lambda_1 (density algebra in the test body)."""
        K, M, L, T = 2, 2, 2, 220
        h = np.array([0.0, 2.0, 1.0, 0.0, 2.0, 2.0, 2.0, 0.0, 3.0])
        model = IOHMMHMix(K=K, M=M, L=L, hyperparams=h)
        datasets, trues = [], []
        for r in range(N_REPS):
            u = np.column_stack([np.ones(T), rng.standard_normal(T)]).astype(np.float32)
            p1 = rng.dirichlet(np.ones(K))
            w = h[0] + h[1] * rng.standard_normal((K, M))
            hypermu = np.sort(h[7] + h[8] * rng.standard_normal(K))
            mu = np.sort(
                hypermu[:, None] + h[2] * rng.standard_normal((K, L)), axis=1
            )
            # lambda row (lam, 1-lam): prod_l lam_l^(h5-1) (1-lam_l)^(h6-1)
            # == lam^(h5+h6-2) (1-lam)^(h5+h6-2) = Beta(h5+h6-1, h5+h6-1)
            lam1 = rng.beta(h[5] + h[6] - 1.0, h[5] + h[6] - 1.0, size=K)
            lam = np.column_stack([lam1, 1.0 - lam1])
            s = truncnorm.rvs(
                (0.0 - h[3]) / h[4], np.inf, loc=h[3], scale=h[4],
                size=(K, L), random_state=rng,
            )
            probs = np.exp(u @ w.T)
            probs /= probs.sum(axis=1, keepdims=True)
            z = np.empty(T, np.int64)
            z[0] = rng.choice(K, p=p1)
            for t in range(1, T):
                z[t] = rng.choice(K, p=probs[t])
            comp = np.array([rng.choice(L, p=lam[zt]) for zt in z])
            x = mu[z, comp] + s[z, comp] * rng.standard_normal(T)
            datasets.append(
                {"x": x.astype(np.float32), "u": u, "mask": np.ones(T, np.float32)}
            )
            trues.append(
                np.concatenate([hypermu, mu.ravel(), [lam1[0], lam1[1]], s.ravel()])
            )
        data = {
            k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]
        }
        cfg = SamplerConfig(num_warmup=150, num_samples=200, num_chains=1, max_treedepth=5)
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(4), cfg, chunk_size=N_REPS)
        assert float(np.asarray(stats["diverging"]).mean()) < 0.15

        units = []
        for i in range(N_REPS):
            draws = model.constrained_draws(qs[i])
            flat = np.column_stack(
                [
                    np.asarray(draws["hypermu_k"]).reshape(-1, K),
                    np.asarray(draws["mu_kl"]).reshape(-1, K * L),
                    np.asarray(draws["lambda_kl"]).reshape(-1, K, L)[:, :, 0],
                    np.asarray(draws["s_kl"]).reshape(-1, K * L),
                ]
            )
            units.append(_ranks(trues[i], flat))
        _uniformity_ok(np.stack(units))


class TestSBCTreeSemisup:
    def test_rank_uniformity(self, rng):
        """Semi-supervised TreeHMM on the 2x2 hierarchical-mixture tree
        (`hhmm/main.R:17-91` structure): the flat expansion of drawn
        tree parameters simulates (z, x); observed top-state labels
        g = group(z) enter via hard gating (the exact conditional
        p(z | g) — the model SBC must be calibrated against; the
        stan-parity soft gate is a deliberate reference-parity
        approximation, `hmm-multinom-semisup.stan:42-44`)."""
        from hhmm_tpu.hhmm.examples import hier2x2_tree

        T = 250
        tmpl = TreeHMM(
            hier2x2_tree(), semisup=True, gate_mode="hard",
            prior_mu_scale=5.0, prior_sigma_scale=2.0,
        )
        K = tmpl.K
        groups = np.asarray(tmpl.groups)
        datasets, trues = [], []
        for r in range(N_REPS):
            params = {}
            for name, _, _, _, support in tmpl._slots:
                row = np.zeros(len(support))
                row[support] = rng.dirichlet(np.ones(int(support.sum())))
                params[name] = row
            mus = []
            for gi, sz in enumerate(tmpl._group_sizes):
                m = np.sort(5.0 * rng.standard_normal(sz))
                params[f"mu_g{gi}"] = m
                mus.append(m)
            mu = np.concatenate(mus)
            sigma = np.abs(2.0 * rng.standard_normal(K)) + 1e-3
            params["sigma"] = sigma
            pi, A = tmpl.assemble({k: jnp.asarray(v) for k, v in params.items()})
            z, x = hmm_sim(
                jax.random.PRNGKey(int(rng.integers(1 << 30))),
                T,
                np.asarray(A),
                np.asarray(pi),
                obsmodel_gaussian(mu, sigma),
                validate=False,
            )
            g = groups[np.asarray(z)]
            datasets.append(
                {
                    "x": np.asarray(x, np.float32),
                    "g": g.astype(np.int32),
                    "mask": np.ones(T, np.float32),
                }
            )
            trues.append(np.concatenate([mu, sigma]))
        data = {
            k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]
        }
        cfg = SamplerConfig(num_warmup=150, num_samples=200, num_chains=1, max_treedepth=5)
        qs, stats = fit_batched(tmpl, data, jax.random.PRNGKey(5), cfg, chunk_size=N_REPS)
        assert float(np.asarray(stats["diverging"]).mean()) < 0.15

        units = []
        for i in range(N_REPS):
            draws = tmpl.constrained_draws(qs[i])
            mu_d = np.column_stack(
                [
                    np.asarray(draws[f"mu_g{gi}"]).reshape(-1, sz)
                    for gi, sz in enumerate(tmpl._group_sizes)
                ]
            )
            flat = np.column_stack([mu_d, np.asarray(draws["sigma"]).reshape(-1, K)])
            units.append(_ranks(trues[i], flat))
        _uniformity_ok(np.stack(units))


class TestSBCMultinomial:
    def test_rank_uniformity(self, rng):
        K, L, T = 2, 3, 250
        model = MultinomialHMM(K=K, L=L)
        datasets, trues = [], []
        for _ in range(N_REPS):
            p1 = rng.dirichlet(np.ones(K))
            A = rng.dirichlet(np.ones(K), size=K)
            phi = rng.dirichlet(np.ones(L), size=K)
            z, x = hmm_sim(
                jax.random.PRNGKey(int(rng.integers(1 << 30))),
                T,
                A,
                p1,
                obsmodel_categorical(phi),
                validate=False,
            )
            datasets.append(
                {"x": np.asarray(x, dtype=np.int32), "mask": np.ones(T, np.float32)}
            )
            trues.append(np.concatenate([[p1[0]], [A[0, 0], A[1, 1]], phi[:, 0]]))
        data = {
            k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]
        }
        # max_treedepth=5 matches the benchmark default (bench.py): this
        # suite is the calibration evidence for that trajectory budget
        cfg = SamplerConfig(
            num_warmup=150, num_samples=200, num_chains=1, max_treedepth=5
        )
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(1), cfg, chunk_size=N_REPS)
        assert float(np.asarray(stats["diverging"]).mean()) < 0.1

        # label switching: the multinomial posterior is invariant under
        # state permutation; canonicalize each draw by sorting states on
        # phi[:, 0] and canonicalize the truth identically
        units = []
        for i in range(N_REPS):
            draws = model.constrained_draws(qs[i])
            p1d = np.asarray(draws["p_1k"]).reshape(-1, K)
            Ad = np.asarray(draws["A_ij"]).reshape(-1, K, K)
            phid = np.asarray(draws["phi_k"]).reshape(-1, K, L)
            order = np.argsort(phid[:, :, 0], axis=1)  # [S, K]
            s_idx = np.arange(p1d.shape[0])[:, None]
            p1d = np.take_along_axis(p1d, order, axis=1)
            phid = phid[s_idx, order]
            Ad = Ad[s_idx[:, :, None], order[:, :, None], order[:, None, :]]
            # canonical truth from the stored raw values
            raw_p1 = np.array([trues[i][0], 1 - trues[i][0]])
            raw_A = np.array(
                [
                    [trues[i][1], 1 - trues[i][1]],
                    [1 - trues[i][2], trues[i][2]],
                ]
            )
            raw_phi0 = trues[i][3:5]
            torder = np.argsort(raw_phi0)
            flat = np.column_stack(
                [
                    p1d[:, 0],
                    Ad[:, 0, 0],
                    Ad[:, 1, 1],
                    phid[:, 0, 0],
                    phid[:, 1, 0],
                ]
            )
            truth = np.array(
                [
                    raw_p1[torder][0],
                    raw_A[torder][:, torder][0, 0],
                    raw_A[torder][:, torder][1, 1],
                    raw_phi0[torder][0],
                    raw_phi0[torder][1],
                ]
            )
            units.append(_ranks(truth, flat))
        _uniformity_ok(np.stack(units))
