"""Async flush pipeline suite (`hhmm_tpu/pipeline/` + the scheduler
and pager wiring — PR 18; see docs/serving.md "Async pipeline").

Pins the PR's contracts:

- **placement** (`pipeline/place.py`): the blake2b consistent hash is
  deterministic across instances (and hash randomization), near-uniform
  over devices, order-preserving under `split`, and recorded into the
  plan manifest stanza from ABOVE the plan layer;
- **in-flight table** (`pipeline/dispatch.py`): FIFO harvest, the
  in-flight series guard, depth/peak accounting, thread-safe under
  churn;
- **THE parity gate**: pipelined serving is bitwise-identical to the
  sync scheduler per (round, series) — same posteriors, same per-draw
  logliks, same draw-health masks — in-process on one device and in
  subprocesses on 2- and 4-virtual-CPU-device meshes
  (`plan.force_host_platform_devices`), with the compile count FLAT
  after warmup;
- **overlap drive**: explicit `dispatch_async`/`harvest` delivers the
  same responses as `flush`, with the fold-order guard deferring (not
  shedding) queued repeats of an airborne series;
- **commit-at-harvest** (invariant 8): a flight that dies shows up as
  shed responses with every series still at its pre-tick filter state;
- **pager coalescing** (the double-load fix): two threads paging the
  same cold snapshot collapse to ONE registry read; and the per-device
  residency partition splits the byte budget so one device's pressure
  cannot evict another device's snapshots.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from hhmm_tpu.models import MultinomialHMM, TayalHHMM
from hhmm_tpu.obs import manifest as obs_manifest
from hhmm_tpu.pipeline import DevicePlacement, Flight, InFlightTable
from hhmm_tpu.serve import (
    MicroBatchScheduler,
    PosteriorSnapshot,
    SnapshotPager,
    SnapshotRegistry,
    model_spec,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fake_snapshot(model, n_draws=4, scale=0.3, seed=0):
    rng = np.random.default_rng(seed)
    draws = (rng.normal(size=(n_draws, model.n_free)) * scale).astype(
        np.float32
    )
    return PosteriorSnapshot(spec=model_spec(model), draws=draws)


def _tayal_stream(n_series, T, seed=0):
    from __graft_entry__ import _tayal_batch

    x, sign = _tayal_batch(n_series, T, seed=seed)
    return np.asarray(x), np.asarray(sign)


def _key(r):
    return (
        r.loglik,
        np.asarray(r.probs).tobytes(),
        np.asarray(r.per_draw_loglik).tobytes(),
        np.asarray(r.draw_ok).tobytes(),
    )


# ---------------------------------------------------------------------------
# placement


class TestDevicePlacement:
    def test_deterministic_across_instances(self):
        a, b = DevicePlacement(4), DevicePlacement(4)
        for i in range(64):
            sid = f"series-{i}"
            assert a.device_of(sid) == b.device_of(sid)
            assert 0 <= a.device_of(sid) < 4

    def test_salt_changes_mapping(self):
        plain, salted = DevicePlacement(8), DevicePlacement(8, salt="z")
        ids = [f"s{i}" for i in range(128)]
        assert any(
            plain.device_of(s) != salted.device_of(s) for s in ids
        )

    def test_near_uniform_spread(self):
        p = DevicePlacement(4)
        counts = [0] * 4
        for i in range(256):
            counts[p.device_of(f"ticker-{i}")] += 1
        # every device owns a non-trivial share of 256 hashed ids
        assert min(counts) >= 256 // 4 // 3, counts

    def test_single_device_shortcut(self):
        p = DevicePlacement(1)
        assert p.device_of("anything") == 0

    def test_split_preserves_order_and_global_index(self):
        p = DevicePlacement(3)
        items = [(f"s{i}", i) for i in range(20)]
        split = p.split(items, key=lambda it: it[0])
        merged = sorted(
            (gi, it) for pairs in split.values() for gi, it in pairs
        )
        assert [it for _, it in merged] == items
        for d, pairs in split.items():
            assert [p.device_of(it[0]) for _, it in pairs] == [d] * len(pairs)
            assert [gi for gi, _ in pairs] == sorted(gi for gi, _ in pairs)

    def test_invalid_width_raises(self):
        with pytest.raises(ValueError, match="n_devices"):
            DevicePlacement(0)

    def test_record_embeds_placement_in_plan_stanza(self):
        from hhmm_tpu.plan import WorkloadShape, make_plan

        plan = make_plan(
            WorkloadShape(B=8, T=16), n_devices=1, platform="cpu"
        )
        DevicePlacement(1, salt="pr18").record(plan)
        stanza = obs_manifest.noted_stanza("plan")
        assert stanza["placement"]["algo"] == "blake2b8-mod"
        assert stanza["placement"]["n_devices"] == 1
        assert stanza["placement"]["salt"] == "pr18"
        # the plan's own stanza keys survive the re-note
        assert len(set(stanza) - {"placement"}) > 0


# ---------------------------------------------------------------------------
# in-flight table


def _flight(fid, series):
    return Flight(
        flush_id=fid,
        kernel="update",
        bucket=8,
        device_index=0,
        group=[(s, {}, 0.0, s, None) for s in series],
        traces=[None] * len(series),
        outputs=None,
        dtype_locks={},
        fn=None,
        fargs=(),
        t_dispatch=0.0,
    )


class TestInFlightTable:
    def test_fifo_and_guard(self):
        t = InFlightTable()
        f1, f2 = _flight(t.next_id(), ["a", "b"]), _flight(t.next_id(), ["c"])
        t.add(f1)
        t.add(f2)
        assert t.depth() == 2
        assert t.guarded("a") and t.guarded("c") and not t.guarded("z")
        assert t.series_in_flight() == {"a", "b", "c"}
        assert t.pop_oldest() is f1  # dispatch order
        assert not t.guarded("a") and t.guarded("c")
        assert t.pop_oldest() is f2
        assert t.pop_oldest() is None
        st = t.stats()
        assert st == {
            "depth": 0,
            "peak_depth": 2,
            "dispatched": 2,
            "harvested": 2,
        }

    def test_refcounted_guard_across_flights(self):
        t = InFlightTable()
        f1, f2 = _flight(t.next_id(), ["a"]), _flight(t.next_id(), ["a"])
        t.add(f1)
        t.add(f2)
        t.pop_oldest()
        assert t.guarded("a")  # the second flight still carries it
        t.pop_oldest()
        assert not t.guarded("a")

    def test_concurrent_add_pop_churn(self):
        t = InFlightTable()
        popped, errs = [], []

        def producer():
            try:
                for i in range(200):
                    t.add(_flight(t.next_id(), [f"s{i % 17}"]))
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        def consumer():
            try:
                n = 0
                while n < 200:
                    f = t.pop_oldest()
                    if f is None:
                        time.sleep(0.0005)
                        continue
                    popped.append(f.flush_id)
                    n += 1
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        th = [
            threading.Thread(target=producer),
            threading.Thread(target=consumer),
        ]
        for x in th:
            x.start()
        for x in th:
            x.join(timeout=30)
        assert not errs
        assert popped == sorted(popped)  # FIFO held under churn
        assert t.depth() == 0 and not t.series_in_flight()


# ---------------------------------------------------------------------------
# scheduler: parity + overlap drive (single device, in-process)


class TestPipelinedScheduler:
    def _run(self, model, x, sign, snap, *, pipeline, drive="flush"):
        B, T = x.shape
        sched = MicroBatchScheduler(
            model, buckets=(8, 16, 32), pipeline=pipeline
        )
        sched.attach_many([(f"s{i}", snap, None) for i in range(B)])
        out = {}
        for t in range(T):
            for i in range(B):
                sched.submit(
                    f"s{i}", {"x": int(x[i, t]), "sign": int(sign[i, t])}
                )
            if drive == "flush":
                batch = sched.flush()
            else:  # explicit overlap drive
                batch = sched.harvest()
                sched.dispatch_async()
                batch += sched.harvest()
            for r in batch:
                out[(t, r.series_id)] = r
        if drive != "flush":
            for r in sched.harvest():
                out[(T, r.series_id)] = r
        return out, sched

    def test_flush_parity_is_bitwise(self):
        model = TayalHHMM(gate_mode="hard")
        B, T = 16, 5
        x, sign = _tayal_stream(B, T, seed=7)
        snap = _fake_snapshot(model)
        sync, _ = self._run(model, x, sign, snap, pipeline=False)
        pipe, sched = self._run(model, x, sign, snap, pipeline=True)
        assert set(sync) == set(pipe)
        for k in sync:
            assert _key(sync[k]) == _key(pipe[k]), k
        st = sched.pipeline_stats()
        assert st["dispatched"] == st["harvested"] == T
        assert st["depth"] == 0 and st["n_devices"] == 1
        assert st["per_device_served"]["0"] == B * T

    def test_overlap_drive_delivers_same_responses(self):
        model = TayalHHMM(gate_mode="hard")
        B, T = 8, 5
        x, sign = _tayal_stream(B, T, seed=9)
        snap = _fake_snapshot(model)
        sync, _ = self._run(model, x, sign, snap, pipeline=False)
        over, sched = self._run(
            model, x, sign, snap, pipeline=True, drive="overlap"
        )
        # overlap shifts WHICH call returns a response (the flight
        # harvests one round later), never its value: compare by series
        by_series_sync: dict = {}
        by_series_over: dict = {}
        for (t, s), r in sync.items():
            by_series_sync.setdefault(s, []).append((t, _key(r)))
        for (t, s), r in over.items():
            by_series_over.setdefault(s, []).append((t, _key(r)))
        assert set(by_series_sync) == set(by_series_over)
        for s in by_series_sync:
            a = [k for _, k in sorted(by_series_sync[s])]
            b = [k for _, k in sorted(by_series_over[s])]
            assert a == b, s

    def test_inflight_guard_defers_queued_repeat(self):
        model = MultinomialHMM(K=2, L=3)
        snap = _fake_snapshot(model)
        sched = MicroBatchScheduler(model, buckets=(4,), pipeline=True)
        sched.attach("s", snap)
        sched.submit("s", {"x": 0})
        assert sched.dispatch_async() == 1
        sched.submit("s", {"x": 1})
        # the airborne flight guards the series: its second tick must
        # NOT dispatch (it would fold from a stale filter state)
        assert sched.dispatch_async() == 0
        assert sched.metrics.inflight_deferred_ticks == 1
        assert len(sched.harvest()) == 1
        assert sched.dispatch_async() == 1  # now its turn
        assert len(sched.harvest()) == 1
        st = sched.pipeline_stats()
        assert st["deferred_ticks"] == 1 and st["harvested"] == 2

    def test_flush_drains_repeats_through_generations(self):
        """`flush()` keeps sync semantics for multi-tick series: queued
        repeats fold in submission order within ONE flush call."""
        model = MultinomialHMM(K=2, L=3)
        snap = _fake_snapshot(model)
        results = {}
        for pipeline in (False, True):
            sched = MicroBatchScheduler(
                model, buckets=(4,), pipeline=pipeline
            )
            sched.attach("s", snap)
            for v in (0, 1, 2):
                sched.submit("s", {"x": v})
            out = sched.flush()
            assert len(out) == 3 and not any(r.shed for r in out)
            results[pipeline] = [_key(r) for r in out]
        assert results[False] == results[True]

    def test_harvest_requires_pipeline_mode(self):
        model = MultinomialHMM(K=2, L=3)
        sched = MicroBatchScheduler(model, buckets=(4,))
        assert sched.pipeline_stats() is None
        with pytest.raises(ValueError, match="pipeline=True"):
            sched.harvest()
        with pytest.raises(ValueError, match="pipeline=True"):
            sched.dispatch_async()

    def test_failed_flight_sheds_without_torn_state(self):
        """Commit-at-harvest (invariant 8): a flight that dies in the
        air sheds its group and every series keeps the filter state it
        had BEFORE the flight dispatched."""
        model = MultinomialHMM(K=2, L=3)
        snap = _fake_snapshot(model)
        sched = MicroBatchScheduler(model, buckets=(4,), pipeline=True)
        for i in range(3):
            sched.attach(f"s{i}", snap)
            sched.submit(f"s{i}", {"x": i % 3})
        assert len(sched.flush()) == 3
        before = {
            f"s{i}": np.asarray(sched.filter_state_of(f"s{i}")[0])
            for i in range(3)
        }
        for i in range(3):
            sched.submit(f"s{i}", {"x": (i + 1) % 3})
        assert sched.dispatch_async() == 1
        # simulate the device dying mid-flight: delete the airborne
        # buffers so the harvest-side sync raises (the same
        # XlaRuntimeError surface a real device loss produces)
        flight = sched._inflight._flights[next(iter(sched._inflight._flights))]
        for leaf in flight.outputs:
            leaf.delete()
        out = sched.harvest()
        assert len(out) == 3 and all(r.shed for r in out)
        assert all("flight failed" in r.error for r in out)
        for i in range(3):
            after = np.asarray(sched.filter_state_of(f"s{i}")[0])
            np.testing.assert_array_equal(before[f"s{i}"], after)
        # the pipeline recovers: the next tick serves normally
        sched.submit("s0", {"x": 1})
        ok = sched.flush()
        assert len(ok) == 1 and not ok[0].shed

    def test_two_thread_submit_harvest_churn(self):
        """Churn smoke: a harvest thread reaps flights while the main
        thread submits + dispatches. No exceptions, no deadlock, and
        every submitted tick is eventually delivered exactly once (the
        in-flight guard + leaf-only pipeline locks keep the planes
        consistent)."""
        model = MultinomialHMM(K=2, L=3)
        snap = _fake_snapshot(model)
        sched = MicroBatchScheduler(model, buckets=(4, 8), pipeline=True)
        B, rounds = 8, 12
        for i in range(B):
            sched.attach(f"s{i}", snap)
        got, errs = [], []
        stop = threading.Event()

        def harvester():
            try:
                while not stop.is_set():
                    got.extend(sched.harvest())
                    time.sleep(0.001)
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        th = threading.Thread(target=harvester)
        th.start()
        try:
            for t in range(rounds):
                for i in range(B):
                    sched.submit(f"s{i}", {"x": (t + i) % 3})
                sched.dispatch_async()
                while sched._inflight.depth() > 0:
                    time.sleep(0.001)
        finally:
            stop.set()
            th.join(timeout=30)
        got.extend(sched.flush())
        assert not errs
        assert len(got) == B * rounds
        assert not any(r.shed for r in got)
        per_series: dict = {}
        for r in got:
            per_series[r.series_id] = per_series.get(r.series_id, 0) + 1
        assert all(n == rounds for n in per_series.values())


# ---------------------------------------------------------------------------
# THE acceptance gate: multi-device subprocess parity

_MULTI_DEVICE_GATE = r'''
import json, sys
sys.path.insert(0, "tests")
from hhmm_tpu.plan import force_host_platform_devices
force_host_platform_devices(int(sys.argv[1]))
import numpy as np
import jax
from test_pipeline import _fake_snapshot, _tayal_stream, _key
from hhmm_tpu.models import TayalHHMM
from hhmm_tpu.pipeline import DevicePlacement
from hhmm_tpu.serve import MicroBatchScheduler

n_dev = int(sys.argv[1])
assert len(jax.devices()) == n_dev, jax.devices()
model = TayalHHMM(gate_mode="hard")
B, T = 256, 4
x, sign = _tayal_stream(B, T, seed=5)
snap = _fake_snapshot(model, n_draws=4)

def run(pipeline):
    placement = DevicePlacement(n_dev) if pipeline else None
    sched = MicroBatchScheduler(
        model, buckets=(8, 32, 64, 128, 256),
        pipeline=pipeline, placement=placement,
    )
    sched.attach_many([(f"s{i}", snap, None) for i in range(B)])
    out, warm = {}, None
    for t in range(T):
        for i in range(B):
            sched.submit(f"s{i}", {"x": int(x[i, t]), "sign": int(sign[i, t])})
        for r in sched.flush():
            out[(t, r.series_id)] = r
        if t == 1:
            warm = sched.metrics.compile_count
    return out, sched, warm

sync, _, _ = run(False)
pipe, sp, warm = run(True)
assert set(sync) == set(pipe)
mismatch = sum(1 for k in sync if _key(sync[k]) != _key(pipe[k]))
st = sp.pipeline_stats()
print(json.dumps({
    "n": len(sync), "mismatch": mismatch,
    "compile_warm": warm, "compile_end": sp.metrics.compile_count,
    "per_device_served": st["per_device_served"],
    "dispatched": st["dispatched"], "harvested": st["harvested"],
}))
'''


class TestMultiDeviceParityGate:
    @pytest.mark.parametrize("n_dev", [2, 4])
    def test_bitwise_parity_and_compile_flat(self, n_dev):
        """256-series replay on an ``n_dev``-virtual-CPU-device mesh:
        pipelined responses bitwise-match the sync scheduler per
        (round, series) — posteriors, per-draw logliks, draw-health
        masks — the compile count is FLAT after warmup, and the
        fan-out actually served every device."""
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # the script forces cpu itself
        out = subprocess.run(
            [sys.executable, "-c", _MULTI_DEVICE_GATE, str(n_dev)],
            capture_output=True,
            text=True,
            timeout=600,
            cwd=REPO,
            env=env,
        )
        assert out.returncode == 0, out.stderr[-3000:]
        rec = json.loads(out.stdout.strip().splitlines()[-1])
        assert rec["n"] == 256 * 4
        assert rec["mismatch"] == 0
        assert rec["compile_end"] == rec["compile_warm"]  # flat
        assert rec["dispatched"] == rec["harvested"]
        served = {int(k): v for k, v in rec["per_device_served"].items()}
        assert len(served) == n_dev
        assert all(v > 0 for v in served.values())
        assert sum(served.values()) == 256 * 4


# ---------------------------------------------------------------------------
# pager: load coalescing + per-device partitions


class _BlockingRegistry:
    """Registry stub whose load blocks until released — the window two
    racing page-ins must collapse in."""

    def __init__(self, snap):
        self.snap = snap
        self.loads = 0
        self.release = threading.Event()
        self.entered = threading.Event()

    def serving_name(self, name):
        return None

    def path(self, name):
        return f"/nonexistent/{name}.npz"

    def load(self, name):
        self.loads += 1
        self.entered.set()
        assert self.release.wait(timeout=30)
        return self.snap


class TestPagerPipelineWiring:
    def test_racing_loads_collapse_to_one_read(self):
        model = MultinomialHMM(K=2, L=3)
        snap = _fake_snapshot(model)
        reg = _BlockingRegistry(snap)
        pager = SnapshotPager(reg, budget_bytes=1 << 20)
        results, errs = [], []

        def racer():
            try:
                results.append(pager.load("hot"))
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        t1 = threading.Thread(target=racer)
        t1.start()
        assert reg.entered.wait(timeout=30)  # owner is inside the load
        t2 = threading.Thread(target=racer)
        t2.start()
        time.sleep(0.05)  # let the racer reach the coalescing wait
        reg.release.set()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert not errs
        assert len(results) == 2 and all(r is snap for r in results)
        assert reg.loads == 1  # ONE underlying .npz read
        assert pager.stats()["load_coalesced"] == 1
        assert pager._loading == {}  # table drained

    def test_failed_load_releases_racers(self):
        model = MultinomialHMM(K=2, L=3)

        class _Broken(_BlockingRegistry):
            def load(self, name):
                self.loads += 1
                self.entered.set()
                assert self.release.wait(timeout=30)
                return None  # corrupt/missing: a miss, not a raise

        reg = _Broken(_fake_snapshot(model))
        pager = SnapshotPager(reg, budget_bytes=1 << 20)
        results = []
        t1 = threading.Thread(target=lambda: results.append(pager.load("x")))
        t1.start()
        assert reg.entered.wait(timeout=30)
        t2 = threading.Thread(target=lambda: results.append(pager.load("x")))
        t2.start()
        time.sleep(0.05)
        reg.release.set()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert results == [None, None]  # both degrade, neither hangs
        assert pager._loading == {}

    def test_per_device_partition_budgets_and_eviction(self, tmp_path):
        model = MultinomialHMM(K=2, L=3)
        reg = SnapshotRegistry(str(tmp_path))
        placement = DevicePlacement(2)
        # pick names with known owners so the test controls pressure
        dev0 = [n for n in (f"p{i}" for i in range(64))
                if placement.device_of(n) == 0][:3]
        dev1 = [n for n in (f"q{i}" for i in range(64))
                if placement.device_of(n) == 1][:1]
        snap = _fake_snapshot(model, n_draws=8)
        for n in dev0 + dev1:
            reg.save(n, snap)
        nbytes = int(np.asarray(snap.draws).nbytes)
        pager = SnapshotPager(reg, budget_bytes=4 * nbytes)
        pager.set_placement(placement)
        assert pager.device_budget_bytes() == 2 * nbytes
        assert pager.touch(dev1[0]) is not None
        for n in dev0:  # 3 snapshots into a 2-snapshot device share
            assert pager.touch(n) is not None
        stats = pager.stats()
        assert stats["device_budget_bytes"] == 2 * nbytes
        per_dev = stats["per_device_bytes"]
        # device 0 shed ITS OWN lru entry; device 1 was never touched
        assert per_dev["0"] <= 2 * nbytes
        assert per_dev["1"] == nbytes
        names = pager.resident_names()
        assert dev0[0] not in names  # LRU victim, same device
        assert dev1[0] in names  # other device's snapshot safe
