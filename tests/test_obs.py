"""Observability subsystem tests (`hhmm_tpu/obs/`, `scripts/bench_diff.py`,
`scripts/obs_report.py`).

Covers the contracts the rest of the stack leans on:

- the metrics plane (`obs/metrics.py`): disabled-mode null singleton
  (hot paths pay one attribute read + branch), labeled instruments,
  histogram quantile edge contract, deterministic snapshot/exports,
  weakref attachment merging, per-chunk interim convergence emission
  from a real `batch/fit.py` run, SLO evaluation + bench_diff SLO
  gating, the obs_report dashboard (rendered without jax);

- span nesting + aggregation determinism (injectable clock — the same
  event multiset must aggregate to the same table, percentiles by
  exact order statistic);
- the disabled-mode fast path (shared no-op singleton, nothing
  recorded, ``sync`` never blocks);
- compile-counter flatness on a re-jitted-twice toy kernel (warm calls
  add zero backend compiles; a new shape adds exactly one trace to the
  registered entry point's cache);
- `serve/metrics.py` routing its compile counter through the telemetry
  registry with the ``summary()`` schema unchanged;
- manifest round-trip + corrupt-file tolerance (`batch/cache.py`
  discipline: quarantine aside, read as miss);
- `scripts/bench_diff.py` pass/fail fixtures AND exit 0 over the
  checked-in BENCH_*.json trajectory;
- `scripts/check_guards.py` invariant 5 (raw ``time.time()`` and
  unregistered serve/bench jits are flagged; the repo passes).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hhmm_tpu.obs import manifest as obs_manifest
from hhmm_tpu.obs import metrics as obs_metrics
from hhmm_tpu.obs import telemetry, trace
from hhmm_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    _NULL_INSTRUMENT,
)
from hhmm_tpu.obs.trace import Tracer, _NULL_SPAN

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")


class _FakeClock:
    """Deterministic clock: +1.0 per read."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


class TestSpans:
    def test_nesting_paths_and_aggregate_determinism(self):
        def run_once():
            t = Tracer(clock=_FakeClock())
            t.enable()
            with t.span("outer"):
                with t.span("inner"):
                    pass
                with t.span("inner"):
                    pass
            return t

        t1, t2 = run_once(), run_once()
        evs = t1.events()
        # completion order: inner, inner, outer; nested paths recorded
        assert [e["name"] for e in evs] == ["inner", "inner", "outer"]
        assert [e["path"] for e in evs] == [
            "outer/inner",
            "outer/inner",
            "outer",
        ]
        agg1, agg2 = t1.aggregate(), t2.aggregate()
        assert agg1 == agg2  # fully deterministic under the fake clock
        assert agg1["inner"]["count"] == 2
        assert agg1["outer"]["count"] == 1
        # fake clock: every span body costs exactly one tick except
        # outer, which spans its children's reads too
        assert agg1["inner"]["total_s"] == pytest.approx(2.0)

    def test_percentiles_exact_order_statistic(self):
        clock = _FakeClock()
        t = Tracer(clock=clock)
        t.enable()
        # 100 spans with durations 1..100 (each __exit__ adds one extra
        # clock read inside _record? no — enter reads once, exit reads
        # once: duration == 1 tick unless we stretch it manually)
        for i in range(100):
            sp = t.span("s")
            sp.__enter__()
            clock.t += i  # stretch: durations 1, 2, ..., 100
            sp.__exit__(None, None, None)
        agg = t.aggregate()["s"]
        durs = sorted(e["dur_s"] for e in t.events())
        assert durs == [float(i) for i in range(1, 101)]
        assert agg["p50_ms"] == pytest.approx(50 * 1e3)
        assert agg["p99_ms"] == pytest.approx(99 * 1e3)
        assert agg["max_ms"] == pytest.approx(100 * 1e3)

    def test_disabled_fast_path_shared_singleton(self):
        t = Tracer()
        t.disable()
        assert t.span("a") is t.span("b") is _NULL_SPAN
        with t.span("a"):
            pass
        t.event("e")
        assert t.events() == []
        # sync on the null span is identity — never blocks, never touches jax
        obj = object()
        assert t.span("x").sync(obj) is obj

    def test_env_flag(self, monkeypatch):
        t = Tracer()
        monkeypatch.delenv("HHMM_TPU_TRACE", raising=False)
        assert not t.enabled()
        # the env read is cached (the disabled fast path must not pay
        # an os.environ lookup per span site): a mid-process change is
        # only seen through use_env()
        monkeypatch.setenv("HHMM_TPU_TRACE", "1")
        assert not t.enabled()
        t.use_env()
        assert t.enabled()
        monkeypatch.setenv("HHMM_TPU_TRACE", "0")
        t.use_env()
        assert not t.enabled()
        # every common falsy spelling DISABLES (a misread would flip
        # the samplers to blocking sync boundaries)
        for v in ("off", "OFF", "FALSE", "No", " 0 "):
            monkeypatch.setenv("HHMM_TPU_TRACE", v)
            t.use_env()
            assert not t.enabled(), v
        t.enable()  # explicit override beats the env
        assert t.enabled()

    def test_bounded_event_log_and_streaming_aggregate(self):
        # a traced serving host emits spans per tick indefinitely: the
        # raw event window is bounded, the aggregate stays exact on
        # count/total/max with a decimated percentile sample
        clock = _FakeClock()
        t = Tracer(clock=clock, max_events=16, sample_cap=8)
        t.enable()
        for i in range(100):
            sp = t.span("tick")
            sp.__enter__()
            clock.t += i  # durations 1, 2, ..., 100
            sp.__exit__(None, None, None)
        assert len(t.events()) == 16  # window, oldest evicted
        assert t.dropped() == 100 - 16
        agg = t.aggregate()["tick"]
        assert agg["count"] == 100  # exact despite eviction
        assert agg["total_s"] == pytest.approx(sum(range(1, 101)))
        assert agg["max_ms"] == pytest.approx(100 * 1e3)
        # percentiles come from the bounded stride sample — within it
        assert 0 < agg["p50_ms"] <= agg["p99_ms"] <= agg["max_ms"]
        # deterministic: an identical run aggregates identically
        clock2 = _FakeClock()
        t2 = Tracer(clock=clock2, max_events=16, sample_cap=8)
        t2.enable()
        for i in range(100):
            sp = t2.span("tick")
            sp.__enter__()
            clock2.t += i
            sp.__exit__(None, None, None)
        assert t2.aggregate() == t.aggregate()
        t.reset()
        assert t.events() == [] and t.dropped() == 0 and t.aggregate() == {}

    def test_traced_decorator_and_annotate(self):
        t = Tracer(clock=_FakeClock())
        t.enable()

        @t.traced("work")
        def f(x):
            return x + 1

        assert f(1) == 2
        with t.span("s") as sp:
            sp.annotate(K=4, branch="seq")
        evs = {e["name"]: e for e in t.events()}
        assert evs["work"]["dur_s"] > 0
        assert evs["s"]["meta"] == {"K": 4, "branch": "seq"}

    def test_jsonl_export_roundtrip(self, tmp_path):
        t = Tracer(clock=_FakeClock())
        t.enable()
        with t.span("a"):
            pass
        path = str(tmp_path / "spans.jsonl")
        n = t.export_jsonl(path)
        lines = [json.loads(line) for line in open(path)]
        assert n == len(lines) == 1
        assert lines[0]["name"] == "a"

    def test_thread_safety_independent_nesting(self):
        import threading

        t = Tracer()
        t.enable()
        errs = []

        def worker(name):
            try:
                for _ in range(50):
                    with t.span(name):
                        with t.span(name + ".in"):
                            pass
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [
            threading.Thread(target=worker, args=(f"w{i}",)) for i in range(4)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errs
        agg = t.aggregate()
        for i in range(4):
            assert agg[f"w{i}"]["count"] == 50
            assert agg[f"w{i}.in"]["count"] == 50
        # nesting never crossed threads: every inner path is its own parent's
        for e in t.events():
            if e["name"].endswith(".in"):
                assert e["path"] == e["name"].replace(".in", "") + "/" + e["name"]


class TestCompileTelemetry:
    def test_compile_counter_flat_on_warm_rejit(self):
        reg = telemetry.CompileRegistry()
        assert reg.install_listeners()
        try:
            f = reg.register_jit("toy", jax.jit(lambda x: x * 2 + 1))
            f(jnp.ones(4)).block_until_ready()
            c_warm = reg.backend_compiles()
            assert c_warm >= 1
            # warm replay, twice: the counter must be FLAT
            f(jnp.ones(4)).block_until_ready()
            f(jnp.ones(4)).block_until_ready()
            assert reg.backend_compiles() == c_warm
            assert reg.jit_cache_sizes()["toy"] == 1
            # a new shape is one new traced signature and >= 1 compile
            f(jnp.ones(8)).block_until_ready()
            assert reg.backend_compiles() > c_warm
            assert reg.jit_cache_sizes()["toy"] == 2
            secs = reg.compile_seconds()
            assert secs.get("backend_compile_duration", 0.0) > 0.0
        finally:
            reg.uninstall_listeners()

    def test_registry_holds_weakrefs_and_prunes_dead(self):
        reg = telemetry.CompileRegistry()
        f = reg.register_jit("gone", jax.jit(lambda x: x))
        f(jnp.ones(2)).block_until_ready()
        assert reg.jit_cache_sizes()["gone"] == 1
        del f
        import gc

        gc.collect()
        # all-dead names are pruned from reads, not reported 0 forever
        assert "gone" not in reg.jit_cache_sizes()
        # re-registering under the same name does not grow the ref list
        for _ in range(5):
            g = reg.register_jit("churn", jax.jit(lambda x: x + 1))
        g(jnp.ones(2)).block_until_ready()
        assert reg.jit_cache_sizes()["churn"] == 1

    def test_serve_metrics_routes_through_scope(self):
        from hhmm_tpu.serve.metrics import ServeMetrics

        m = ServeMetrics()
        m.set_compile_count(7)
        assert m.compile_count == 7
        # the registry sees the serving counter without knowing the class
        assert telemetry.scope_counts().get("serve.compile_count", 0) >= 7
        # summary schema keys unchanged (bench.py --serve / test_serve.py
        # consumers)
        s = m.summary()
        assert s["compile_count"] == 7
        assert set(s) == {
            "requests",
            "ticks",
            "flushes",
            "ticks_per_sec",
            "latency_p50_ms",
            "latency_p90_ms",
            "latency_p99_ms",
            "degraded_responses",
            "degraded_attaches",
            "superseded_responses",
            "shed_ticks",
            "rejected_attaches",
            "dispatch_errors",
            "device_loss_events",
            "compile_count",
            "h2d_bytes",
            "d2h_bytes",
            "carry_resident_bytes",
        }

    def test_sample_memory_tolerant(self):
        # CPU backend hides memory_stats: must be {} (not an exception),
        # and the peak watermark stays a dict
        out = telemetry.sample_memory()
        assert isinstance(out, dict)
        assert isinstance(telemetry.peak_memory(), dict)


class TestDispatchSpans:
    def test_branch_recorded_in_span_table(self):
        from hhmm_tpu.kernels.dispatch import (
            ffbs_dispatch,
            forward_filter_dispatch,
        )

        K, T = 3, 16
        log_pi = jnp.log(jnp.full((K,), 1.0 / K))
        log_A = jnp.log(jnp.full((K, K), 1.0 / K))
        log_obs = jnp.zeros((T, K))
        trace.tracer.enable()
        base = trace.events()
        try:
            forward_filter_dispatch(log_pi, log_A, log_obs)
            forward_filter_dispatch(
                log_pi, log_A, log_obs, time_parallel=True
            )
            ffbs_dispatch(jax.random.PRNGKey(0), log_pi, log_A, log_obs)
            names = {e["name"] for e in trace.events()[len(base) :]}
        finally:
            trace.tracer.use_env()
            trace.reset()
        assert "kernels.dispatch.forward_filter[seq]" in names
        assert "kernels.dispatch.forward_filter[assoc]" in names
        assert "kernels.dispatch.ffbs[fused]" in names
        # the kernels themselves contribute spans nested under dispatch
        assert "kernels.forward_filter" in names
        assert "kernels.ffbs" in names


class TestManifest:
    def test_roundtrip_atomic(self, tmp_path):
        man = obs_manifest.collect_manifest(
            config={"series": 8, "T": 128}, seed=42
        )
        assert man["version"] == obs_manifest.MANIFEST_VERSION
        assert man["versions"]["jax"] == jax.__version__
        assert man["workload_digest"]
        assert man["backend"] == "cpu"
        path = str(tmp_path / "manifest.json")
        obs_manifest.write_manifest(path, man)
        man2 = obs_manifest.load_manifest(path)
        # round-trip through JSON: identity up to JSON-representable types
        assert man2 == json.loads(json.dumps(man, default=str))

    def test_workload_digest_tracks_config(self):
        m1 = obs_manifest.collect_manifest(config={"T": 128}, seed=1)
        m2 = obs_manifest.collect_manifest(config={"T": 128}, seed=1)
        m3 = obs_manifest.collect_manifest(config={"T": 256}, seed=1)
        assert m1["workload_digest"] == m2["workload_digest"]
        assert m1["workload_digest"] != m3["workload_digest"]

    def test_observability_flags_do_not_fork_workload_digest(self):
        """The bench_diff comparability key must be blind to output
        paths/profiler flags — otherwise adding --manifest-out in CI
        makes every record its own baseline and the gate fails open."""
        import argparse

        import bench

        def ns(**over):
            base = {
                "series": 256, "T": 1024, "sampler": "gibbs",
                "manifest_out": None, "profile": None,
            }
            base.update(over)
            return argparse.Namespace(**base)

        a1, a2 = ns(), ns(manifest_out="/tmp/m.json", profile="/tmp/prof")
        m1 = obs_manifest.collect_manifest(
            config=vars(a1), workload_config=bench.workload_config(a1)
        )
        m2 = obs_manifest.collect_manifest(
            config=vars(a2), workload_config=bench.workload_config(a2)
        )
        assert m1["workload_digest"] == m2["workload_digest"]
        a3 = ns(T=2048)  # a REAL workload change still forks the key
        m3 = obs_manifest.collect_manifest(
            config=vars(a3), workload_config=bench.workload_config(a3)
        )
        assert m1["workload_digest"] != m3["workload_digest"]

    def test_missing_and_corrupt_tolerated(self, tmp_path, capsys):
        assert obs_manifest.load_manifest(str(tmp_path / "nope.json")) is None
        bad = tmp_path / "torn.json"
        bad.write_bytes(b'{"version": 1, "half-writ')
        assert obs_manifest.load_manifest(str(bad)) is None
        # quarantined aside so a re-write under the same name works
        assert not bad.exists()
        assert (tmp_path / "torn.json.corrupt").exists()
        # a JSON file that isn't a manifest is corrupt too
        notman = tmp_path / "not_manifest.json"
        notman.write_text('{"hello": "world"}')
        assert obs_manifest.load_manifest(str(notman)) is None

    def test_manifest_stanza_compact(self):
        st = obs_manifest.manifest_stanza(config={"T": 64})
        assert "spans" not in st and "argv" not in st
        assert {"workload_digest", "span_count", "backend_compiles"} <= set(st)


class TestMetricsRegistry:
    def test_disabled_fast_path_shared_null_singleton(self):
        r = MetricsRegistry(enabled=False)
        assert (
            r.counter("a")
            is r.gauge("b")
            is r.histogram("c")
            is _NULL_INSTRUMENT
        )
        r.counter("a").inc(5)
        r.gauge("b").set(1.0)
        r.histogram("c").observe(0.1)
        assert r.snapshot() == {}  # nothing recorded, nothing allocated

    def test_module_registry_follows_tracer_flag(self, monkeypatch):
        monkeypatch.delenv("HHMM_TPU_TRACE", raising=False)
        trace.tracer.use_env()
        obs_metrics.use_env()
        try:
            assert not obs_metrics.enabled()
            assert obs_metrics.counter("x") is _NULL_INSTRUMENT
            trace.tracer.enable()
            assert obs_metrics.enabled()  # one flag lights the stack
            assert obs_metrics.counter("x") is not _NULL_INSTRUMENT
            obs_metrics.disable()  # explicit override beats the tracer
            assert not obs_metrics.enabled()
        finally:
            trace.tracer.use_env()
            obs_metrics.use_env()
            obs_metrics.reset()

    def test_labeled_instruments_and_snapshot_determinism(self):
        r = MetricsRegistry(enabled=True)
        r.counter("fit.divergences", sampler="nuts").inc(3)
        r.counter("fit.divergences", sampler="nuts").inc(2)  # same instrument
        r.counter("fit.divergences", sampler="gibbs").inc(1)
        r.gauge("fit.interim.rhat_max", chunk="2").set(1.07)
        snap = r.snapshot()
        assert snap["fit.divergences{sampler=nuts}"]["value"] == 5
        assert snap["fit.divergences{sampler=gibbs}"]["value"] == 1
        assert snap["fit.interim.rhat_max{chunk=2}"]["value"] == 1.07
        assert list(snap) == sorted(snap)  # deterministic ordering

    def test_kind_mismatch_rejected(self):
        r = MetricsRegistry(enabled=True)
        r.counter("x").inc()
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("x")

    def test_histogram_quantile_edge_cases(self):
        h = Histogram(edges=[1.0, 2.0, 4.0])
        # empty histogram: no data is NOT zero latency
        assert np.isnan(h.quantile(0.5))
        # single observation: every quantile (q=0 included) reads its
        # bucket's conservative upper edge
        h.observe(3.0)
        assert h.quantile(0.0) == 4.0
        assert h.quantile(0.5) == 4.0
        assert h.quantile(1.0) == 4.0
        # out-of-range observation lands in the unbounded overflow
        # bucket: the tail quantile must read inf, not the last edge
        h.observe(100.0)
        assert h.quantile(1.0) == float("inf")
        assert h.quantile(0.25) == 4.0  # the in-range mass is unaffected
        # q=0 reads the FIRST non-empty bucket, not the smallest edge
        h2 = Histogram(edges=[1.0, 2.0, 4.0])
        h2.observe(1.5)
        assert h2.quantile(0.0) == 2.0

    def test_histogram_merge_and_validation(self):
        a, b = Histogram([1.0, 2.0]), Histogram([1.0, 2.0])
        a.observe(0.5)
        b.observe(1.5, n=3)
        a.merge_from(b)
        assert a.total == 4 and a.counts.tolist() == [1, 3, 0]
        with pytest.raises(ValueError, match="different edges"):
            a.merge_from(Histogram([1.0, 3.0]))
        with pytest.raises(ValueError, match="increasing"):
            Histogram([2.0, 1.0])

    def test_attach_merges_and_prunes_dead(self):
        r = MetricsRegistry(enabled=False)  # attachment ignores the flag
        c1, c2 = Counter(), Counter()
        c1.inc(2)
        c2.inc(3)
        g1, g2 = Gauge(), Gauge()
        g1.set(1.0)
        g2.set(7.0)
        r.attach("serve.requests", c1)
        r.attach("serve.requests", c2)
        r.attach("serve.staleness", g1)
        r.attach("serve.staleness", g2)
        snap = r.snapshot()
        assert snap["serve.requests"]["value"] == 5  # counters sum
        assert snap["serve.staleness"]["value"] == 7.0  # gauges: watermark
        del c2, g2
        import gc

        gc.collect()
        snap = r.snapshot()
        assert snap["serve.requests"]["value"] == 2
        assert snap["serve.staleness"]["value"] == 1.0

    def test_jsonl_export_atomic_roundtrip(self, tmp_path):
        r = MetricsRegistry(enabled=True)
        r.counter("a", k="v").inc(2)
        r.histogram("h", edges=[1.0]).observe(0.5)
        path = str(tmp_path / "metrics.jsonl")
        n = r.export_jsonl(path)
        lines = [json.loads(line) for line in open(path)]
        assert n == len(lines) == 2
        by_key = {line["key"]: line for line in lines}
        assert by_key["a{k=v}"]["value"] == 2
        assert by_key["a{k=v}"]["labels"] == {"k": "v"}
        assert by_key["h"]["counts"] == [1, 0]

    def test_prometheus_exposition(self):
        r = MetricsRegistry(enabled=True)
        r.counter("fit.divergences", sampler="nuts").inc(4)
        r.histogram("serve.lat", edges=[0.01, 0.1]).observe(0.05)
        text = r.to_prometheus()
        assert "# TYPE fit_divergences counter" in text
        assert 'fit_divergences{sampler="nuts"} 4' in text
        # cumulative buckets + the mandatory +Inf bucket and _sum/_count
        assert 'serve_lat_bucket{le="0.01"} 0' in text
        assert 'serve_lat_bucket{le="0.1"} 1' in text
        assert 'serve_lat_bucket{le="+Inf"} 1' in text
        assert "serve_lat_count 1" in text

    def test_record_sampler_health_tolerates_tracers(self):
        # the vmapped fit path calls samplers under jit: stats are
        # tracers there, and emission must be a silent no-op, not an
        # error that breaks the trace
        import jax as _jax

        r_backup = obs_metrics.registry._enabled
        obs_metrics.enable()
        try:

            @_jax.jit
            def traced_call(x):
                obs_metrics.record_sampler_health(
                    "nuts", {"diverging": x, "chain_healthy": x > 0}
                )
                return x * 2

            assert float(traced_call(jnp.asarray(3.0))) == 6.0
            # concrete stats DO emit
            obs_metrics.record_sampler_health(
                "nuts",
                {
                    "diverging": np.array([[True, False]]),
                    "chain_healthy": np.array([True, False]),
                },
            )
            snap = obs_metrics.snapshot()
            assert snap["infer.divergences{sampler=nuts}"]["value"] == 1
            assert snap["infer.quarantined_chains{sampler=nuts}"]["value"] == 1
        finally:
            obs_metrics.registry._enabled = r_backup
            obs_metrics.reset()


class TestServeMetricsPlane:
    def test_quantile_contract_through_summary(self):
        from hhmm_tpu.serve.metrics import ServeMetrics

        m = ServeMetrics()
        # empty window: JSON null, not NaN
        assert m.summary()["latency_p50_ms"] is None
        assert np.isnan(m.quantile(0.5))
        # single observation: q=0 and q=1 both read its bucket edge
        m.observe_latency(0.005)
        assert m.quantile(0.0) == m.quantile(1.0) > 0.0
        # beyond the last edge (60 s): pathological tail reads "inf"
        m.observe_latency(120.0)
        assert m.summary()["latency_p99_ms"] == "inf"

    def test_staleness_gauge_and_peak(self):
        from hhmm_tpu.serve.metrics import ServeMetrics

        m = ServeMetrics()
        assert np.isnan(m.staleness_seconds())
        m.observe_staleness(3.0)
        m.observe_staleness(9.0)
        m.observe_staleness(5.0)
        assert m.staleness_seconds() == 5.0  # gauge: latest
        assert m.peak_staleness_seconds() == 9.0  # watermark: worst
        m.reset_throughput_window()  # new window, new watermark
        assert np.isnan(m.peak_staleness_seconds())

    def test_instruments_attached_to_shared_plane(self):
        from hhmm_tpu.serve.metrics import ServeMetrics

        m = ServeMetrics()
        m.observe_latency(0.001, n=4)
        m.observe_flush(4, 0.5)
        snap = obs_metrics.snapshot()
        # attached regardless of the enabled flag (product metrics)
        assert snap["serve.requests"]["value"] >= 4
        assert snap["serve.ticks"]["value"] >= 4
        assert snap["serve.tick_latency_seconds"]["count"] >= 4

    def test_scheduler_publishes_staleness(self):
        # the scheduler records attach times and publishes the oldest
        # posterior's age on every flush — through the real tick path
        from hhmm_tpu.models import GaussianHMM, NIGPrior
        from hhmm_tpu.serve import MicroBatchScheduler, snapshot_from_fit

        model = GaussianHMM(
            K=2, nig_prior=NIGPrior(m0=0.0, kappa0=0.1, a0=2.0, b0=1.0)
        )
        rng = np.random.default_rng(0)
        samples = rng.normal(size=(1, 16, model.n_free))
        snap = snapshot_from_fit(model, samples, n_draws=4)
        sched = MicroBatchScheduler(model, buckets=(4,))
        sched.attach("s0", snap)
        sched.tick({"s0": {"x": 0.3}})
        assert sched.metrics.staleness_seconds() > 0.0
        assert sched.metrics.peak_staleness_seconds() >= (
            sched.metrics.staleness_seconds()
        )


class TestLoglikCUSUM:
    def test_no_alarm_on_stationary_stream(self):
        from hhmm_tpu.serve.online import LoglikCUSUM

        det = LoglikCUSUM(calibrate=32)
        rng = np.random.default_rng(0)
        alarms = 0
        for x in rng.normal(-1.2, 0.3, size=400):
            _, drifted = det.update(x)
            alarms += drifted
        assert alarms == 0

    def test_alarm_on_sustained_drop_then_rearms(self):
        from hhmm_tpu.serve.online import LoglikCUSUM

        det = LoglikCUSUM(calibrate=32)
        rng = np.random.default_rng(1)
        for x in rng.normal(-1.2, 0.3, size=64):
            det.update(x)
        # sustained downward shift in predictive loglik = stale model
        drift_tick = None
        for t, x in enumerate(rng.normal(-2.4, 0.3, size=64)):
            _, drifted = det.update(x)
            if drifted:
                drift_tick = t
                break
        assert drift_tick is not None and drift_tick < 16  # prompt
        assert det.alarms == 1
        assert det.stat == 0.0  # reset: the next alarm needs NEW drift

    def test_nonfinite_increment_counts_as_maximal_drop(self):
        from hhmm_tpu.serve.online import LoglikCUSUM

        det = LoglikCUSUM(calibrate=4, threshold=2.0)
        for x in (-1.0, -1.1, -0.9, -1.0):
            det.update(x)
        # a quarantined stream's -inf floor: alarms fast, never NaNs
        fired = False
        for _ in range(4):
            _, drifted = det.update(float("-inf"))
            fired = fired or drifted
        assert fired and np.isfinite(det.stat)

    def test_alarm_counter_reaches_metrics_plane(self):
        from hhmm_tpu.serve.online import LoglikCUSUM

        obs_metrics.enable()
        try:
            det = LoglikCUSUM(calibrate=2, threshold=1.0)
            det.update(-1.0)
            det.update(-1.0)
            for _ in range(8):
                det.update(-50.0)
            assert det.alarms >= 1
            assert (
                obs_metrics.snapshot()["serve.drift_alarms"]["value"]
                >= det.alarms
            )
        finally:
            obs_metrics.use_env()
            obs_metrics.reset()


class TestFitInterimEmission:
    def test_per_chunk_convergence_series(self):
        """A traced fit exports interim R̂/ESS/divergence/quarantine per
        chunk — the ISSUE's 'visible while it runs' acceptance gate."""
        from hhmm_tpu.batch import fit_batched
        from hhmm_tpu.infer import GibbsConfig
        from hhmm_tpu.models import GaussianHMM, NIGPrior
        from hhmm_tpu.sim import hmm_sim, obsmodel_gaussian

        K, T, B = 2, 40, 2
        A = np.array([[0.9, 0.1], [0.2, 0.8]])
        xs = []
        for i in range(B):
            _, x = hmm_sim(
                jax.random.PRNGKey(i),
                T,
                A,
                np.ones(K) / K,
                obsmodel_gaussian(np.array([-1.0, 1.0]), np.array([0.5, 0.5])),
            )
            xs.append(np.asarray(x))
        model = GaussianHMM(
            K=K, nig_prior=NIGPrior(m0=0.0, kappa0=0.1, a0=2.0, b0=1.0)
        )
        obs_metrics.enable()
        try:
            fit_batched(
                model,
                {"x": np.stack(xs)},
                jax.random.PRNGKey(0),
                GibbsConfig(num_warmup=4, num_samples=12, num_chains=2),
                chunk_size=1,
            )
            snap = obs_metrics.snapshot()
            for chunk in ("1", "2"):
                rhat = snap[f"fit.interim.rhat_max{{chunk={chunk}}}"]["value"]
                ess = snap[f"fit.interim.ess_min{{chunk={chunk}}}"]["value"]
                assert rhat is not None and rhat >= 1.0
                assert ess is not None and ess > 0.0
                assert (
                    snap[f"fit.interim.divergence_rate{{chunk={chunk}}}"]["value"]
                    == 0.0
                )
                assert (
                    snap[f"fit.interim.quarantined_series{{chunk={chunk}}}"][
                        "value"
                    ]
                    == 0.0
                )
            assert snap["fit.chunks"]["value"] == 2
            assert snap["fit.divergences"]["value"] == 0
            assert snap["fit.quarantined_series"]["value"] == 0
        finally:
            obs_metrics.use_env()
            obs_metrics.reset()

    def test_disabled_fit_emits_nothing(self):
        # with the plane off, the same counters must not exist: the hot
        # path took the one-attribute-read-and-branch exit
        assert not obs_metrics.enabled()
        snap = obs_metrics.snapshot()
        assert not any(k.startswith("fit.") for k in snap)


class TestDiagnosticsDivergences:
    def test_summary_surfaces_divergence_counts(self):
        from hhmm_tpu.infer.diagnostics import summary

        rng = np.random.default_rng(0)
        samples = {"mu": rng.normal(size=(2, 50, 3))}
        div = np.zeros((2, 50), bool)
        div[0, :5] = True
        out = summary(samples, diverging=div)
        assert out["mu"]["divergences"] == 5
        assert out["mu"]["divergence_rate"] == pytest.approx(0.05)
        # opt-out: schema unchanged when not passed
        assert "divergences" not in summary(samples)["mu"]

    def test_divergences_respect_health_mask(self):
        from hhmm_tpu.infer.diagnostics import summary

        rng = np.random.default_rng(1)
        samples = {"mu": rng.normal(size=(2, 40))}
        div = np.zeros((2, 40), bool)
        div[1, :] = True  # all divergences live on the quarantined chain
        out = summary(
            samples, health=np.array([True, False]), diverging=div
        )
        # counted over the same chains as the statistics
        assert out["mu"]["divergences"] == 0
        assert out["mu"]["chains_quarantined"] == 1
        with pytest.raises(ValueError, match="chains"):
            summary(samples, diverging=np.zeros((3, 40), bool),
                    health=np.array([True, False]))


class TestEssManyChunkBoundary:
    def test_chunk_512_exact_and_straddling(self):
        """`ess_many(chunk=512)` must agree with per-row `ess` when N
        lands exactly on the chunk size and when it straddles it —
        the boundary slice must not drop or duplicate row 512."""
        from hhmm_tpu.infer.diagnostics import ess, ess_many

        rng = np.random.default_rng(7)
        for N in (512, 513):
            x = rng.normal(size=(N, 2, 64))
            # make the boundary rows distinctive so an off-by-one slice
            # cannot accidentally agree
            x[511] = np.cumsum(x[511], axis=-1)  # autocorrelated: low ESS
            if N > 512:
                x[512] = np.cumsum(x[512], axis=-1)
            got = ess_many(x, chunk=512)
            assert got.shape == (N,)
            for i in (0, 255, 511, N - 1):
                assert got[i] == pytest.approx(ess(x[i]), rel=1e-10), (N, i)

    def test_non_finite_rows_zero_across_boundary(self):
        from hhmm_tpu.infer.diagnostics import ess_many

        rng = np.random.default_rng(8)
        x = rng.normal(size=(513, 2, 16))
        x[511, 0, 0] = np.nan
        x[512, 1, -1] = np.inf
        got = ess_many(x, chunk=512)
        assert got[511] == 0.0 and got[512] == 0.0
        assert np.all(got[:511] > 0)


class TestSLO:
    def test_attained_and_unmet(self):
        from hhmm_tpu.serve.metrics import SLOSpec, evaluate_slo

        spec = SLOSpec(
            p99_latency_ms=50.0, max_staleness_s=10.0,
            max_post_warmup_recompiles=0,
        )
        ok = evaluate_slo(
            spec, p99_latency_ms=12.5, staleness_s=3.0,
            post_warmup_recompiles=0,
        )
        assert ok["attained"] and all(c["ok"] for c in ok["checks"].values())
        bad = evaluate_slo(
            spec, p99_latency_ms=80.0, staleness_s=3.0,
            post_warmup_recompiles=2,
        )
        assert not bad["attained"]
        assert not bad["checks"]["p99_latency_ms"]["ok"]
        assert not bad["checks"]["post_warmup_recompiles"]["ok"]
        assert bad["checks"]["staleness_s"]["ok"]

    def test_unmeasured_and_pathological_fail(self):
        from hhmm_tpu.serve.metrics import SLOSpec, evaluate_slo

        spec = SLOSpec()
        # an empty window cannot CLAIM attainment
        out = evaluate_slo(
            spec, p99_latency_ms=None, staleness_s=float("nan"),
            post_warmup_recompiles=0,
        )
        assert not out["attained"]
        assert out["checks"]["p99_latency_ms"]["reason"] == "unmeasured"
        assert out["checks"]["staleness_s"]["reason"] == "unmeasured"
        # the summary() "inf" overflow encoding fails, not crashes
        out2 = evaluate_slo(
            spec, p99_latency_ms="inf", staleness_s=1.0,
            post_warmup_recompiles=0,
        )
        assert not out2["checks"]["p99_latency_ms"]["ok"]
        json.dumps(out2)  # JSON-ready for the manifest stanza


def _run_bench_diff(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_diff.py"), *argv],
        capture_output=True,
        text=True,
    )


def _write_fixture_rounds(
    d, values, stamped=True, traced=None, slo=None, escaped=None, request=None,
    duel=None, parity=None, adapt=None, pipeline=None,
):
    for n, v in enumerate(values, start=1):
        rec = {
            "metric": "fixture_throughput",
            "value": v,
            "unit": "series/sec",
            "backend": "cpu",
        }
        if stamped:
            rec["manifest"] = {
                "workload_digest": "wfix",
                "device_kind": "cpu",
                "versions": {"jax": "0.0-test"},
                "trace_enabled": bool(traced[n - 1]) if traced else False,
            }
            if request is not None and request[n - 1] is not None:
                spread, qshare = request[n - 1]
                rec["manifest"]["request"] = {
                    "window_s": 60.0,
                    "tenants": {},
                    "overall": {"ticks": 100, "queue_share": qshare},
                    "fairness": {"p99_spread_ms": spread},
                }
            if escaped is not None and escaped[n - 1] is not None:
                rec["manifest"]["storm"] = {
                    "faults_escaped": int(escaped[n - 1])
                }
            if duel is not None and duel[n - 1] is not None:
                fifo_ms, drr_ms = duel[n - 1]
                rec["manifest"].setdefault("storm", {})["fairness"] = {
                    "fifo_p99_spread_ms": fifo_ms,
                    "drr_p99_spread_ms": drr_ms,
                }
            if parity is not None and parity[n - 1] is not None:
                rec["manifest"].setdefault("storm", {})["warm_page_in"] = {
                    "parity": bool(parity[n - 1])
                }
            if pipeline is not None and pipeline[n - 1] is not None:
                sync_q, async_q, mism = pipeline[n - 1]
                rec["manifest"]["pipeline"] = {
                    "sync_queue_share": sync_q,
                    "async_queue_share": async_q,
                    "overlap_share": 0.99,
                    "parity_mismatches": mism,
                    "ok": bool(
                        isinstance(sync_q, (int, float))
                        and isinstance(async_q, (int, float))
                        and async_q < sync_q
                        and mism == 0
                    ),
                }
            if adapt is not None and adapt[n - 1] is not None:
                tracking, breaches = adapt[n - 1]
                rec["manifest"]["adapt"] = {
                    "tracking_advantage": bool(tracking),
                    "floor_breaches": int(breaches),
                    "rejuvenations": 3,
                    "escalations": 1,
                }
            if slo is not None and slo[n - 1] is not None:
                attained = bool(slo[n - 1])
                rec["manifest"]["slo"] = {
                    "attained": attained,
                    "spec": {"p99_latency_ms": 50.0},
                    "checks": {
                        "p99_latency_ms": {
                            "observed": 10.0 if attained else 90.0,
                            "limit": 50.0,
                            "ok": attained,
                        }
                    },
                }
        (d / f"BENCH_r{n:02d}.json").write_text(
            json.dumps({"n": n, "rc": 0, "parsed": rec})
        )


class TestBenchDiff:
    def test_checked_in_trajectory_exits_zero(self):
        proc = _run_bench_diff("--dir", REPO)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        # a readable per-metric delta table
        assert "tayal_batched_posterior_throughput" in proc.stdout
        assert "Δ%" in proc.stdout

    def test_regression_fails(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 80.0])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "REGRESSION" in proc.stdout

    def test_within_threshold_passes(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 95.0])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "ok vs round" in proc.stdout

    def test_improvement_passes(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 140.0])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout

    def test_unstamped_records_never_gate(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 10.0], stamped=False)
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "ungated" in proc.stdout

    def test_crashed_round_reported_not_fatal(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 99.0])
        (tmp_path / "BENCH_r03.json").write_text(
            json.dumps({"n": 3, "rc": 1, "tail": "Traceback ...", "parsed": None})
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "CRASHED" in proc.stdout

    def test_threshold_flag(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 95.0])
        proc = _run_bench_diff("--dir", str(tmp_path), "--threshold", "2")
        assert proc.returncode == 1, proc.stdout

    def test_trace_regime_never_gates_across(self, tmp_path):
        # a traced run pays sync + span overhead: it must not gate
        # against an untraced baseline of the same workload (each
        # regime is its own comparability key)
        _write_fixture_rounds(
            tmp_path, [100.0, 10.0], traced=[False, True]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert proc.stdout.count("baseline for its workload/stack key") == 2

    def test_trace_regime_gates_within(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 80.0], traced=[True, True]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "REGRESSION" in proc.stdout


class TestBenchDiffSLO:
    def test_slo_regression_fails(self, tmp_path):
        # same throughput, but the serving objectives went from
        # attained to unmet: that IS a regression
        _write_fixture_rounds(tmp_path, [100.0, 100.0], slo=[True, False])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "SLO REGRESSION" in proc.stdout
        assert "p99_latency_ms" in proc.stdout  # names the unmet check

    def test_attained_to_attained_passes(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 98.0], slo=[True, True])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "SLO attained" in proc.stdout

    def test_first_unmet_reported_not_gated(self, tmp_path):
        # no attained baseline to regress from: visible, not fatal
        _write_fixture_rounds(tmp_path, [100.0, 99.0], slo=[False, False])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "SLO unmet (no attained baseline)" in proc.stdout

    def test_recovery_then_regression_gates_again(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 100.0, 100.0], slo=[False, True, False]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert proc.stdout.count("SLO REGRESSION") == 1


class TestBenchDiffResilience:
    """The `bench.py --serve-storm` ``storm`` stanza gates like SLOs:
    clean baseline -> escaped faults is a survival regression."""

    def test_escaped_after_clean_baseline_fails(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 100.0], escaped=[0, 2])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "RESILIENCE REGRESSION" in proc.stdout

    def test_clean_to_clean_passes(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 99.0], escaped=[0, 0])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "faults contained" in proc.stdout

    def test_first_escaped_reported_not_gated(self, tmp_path):
        # no clean baseline to regress from: visible, not fatal
        _write_fixture_rounds(tmp_path, [100.0, 99.0], escaped=[1, 1])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "no clean baseline" in proc.stdout


class TestBenchDiffFairnessDuel:
    """The storm stanza's FIFO-vs-DRR fairness duel gates WITHIN the
    record (the duel ships its own baseline arm); warm page-in parity
    gates on a true -> false transition like the SLO."""

    def test_duel_holds_passes(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 99.0], duel=[(8.0, 0.5), (8.0, 0.4)]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "fair order holds" in proc.stdout

    def test_duel_equality_fails_even_on_first_record(self, tmp_path):
        # strictly below: equal spread means DRR bought nothing, and
        # no prior record is needed to see it
        _write_fixture_rounds(tmp_path, [100.0], duel=[(5.0, 5.0)])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "FAIRNESS REGRESSION" in proc.stdout

    def test_duel_inversion_fails(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 100.0], duel=[(8.0, 0.5), (5.0, 6.0)]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "FAIRNESS REGRESSION" in proc.stdout

    def test_duel_unmeasured_arm_fails(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0], duel=[(None, 0.5)])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout

    def test_parity_lost_after_baseline_fails(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 100.0], parity=[True, False]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "WARM PAGE-IN REGRESSION" in proc.stdout

    def test_parity_never_met_reported_not_gated(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 99.0], parity=[False, False]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "parity unmet" in proc.stdout

    def test_parity_held_passes(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 99.0], parity=[True, True]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "warm page-in parity" in proc.stdout


class TestBenchDiffPipeline:
    """The `bench.py --pipeline` ``pipeline`` stanza gates within the
    record like the FIFO-vs-DRR duel: the stanza ships its own sync
    baseline arm, so the async arm's queue share must sit strictly
    below it with zero parity mismatches — no prior record needed."""

    def test_overlap_holds_passes(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 99.0],
            pipeline=[(0.34, 0.01, 0), (0.33, 0.008, 0)],
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "pipeline overlap holds" in proc.stdout

    def test_equality_fails_even_on_first_record(self, tmp_path):
        # strictly below: equal queue share means the double-buffered
        # split hid nothing, and no prior record is needed to see it
        _write_fixture_rounds(tmp_path, [100.0], pipeline=[(0.2, 0.2, 0)])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "PIPELINE REGRESSION" in proc.stdout

    def test_inversion_fails(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0], pipeline=[(0.1, 0.3, 0)])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "PIPELINE REGRESSION" in proc.stdout

    def test_parity_mismatch_fails(self, tmp_path):
        # a queue-share win bought by serving different posteriors is
        # not a win
        _write_fixture_rounds(tmp_path, [100.0], pipeline=[(0.3, 0.01, 2)])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "PIPELINE REGRESSION" in proc.stdout
        assert "parity mismatch" in proc.stdout

    def test_unmeasured_arm_fails(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0], pipeline=[(None, 0.01, 0)])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout


class TestBenchDiffAdaptation:
    """The `bench.py --adapt` ``adapt`` stanza gates like resilience:
    a tracking baseline -> tracking lost, or a clean ESS baseline ->
    series below the floor, is an adaptation regression; without the
    matching baseline both report ungated."""

    def test_tracking_lost_after_baseline_fails(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 100.0], adapt=[(True, 0), (False, 0)]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "ADAPTATION REGRESSION" in proc.stdout

    def test_tracking_held_passes(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 99.0], adapt=[(True, 0), (True, 0)]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "adaptation tracking" in proc.stdout

    def test_first_stale_reported_not_gated(self, tmp_path):
        # no tracking baseline to regress from: visible, not fatal
        _write_fixture_rounds(
            tmp_path, [100.0, 99.0], adapt=[(False, 0), (False, 0)]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "not tracking (no tracking baseline)" in proc.stdout

    def test_floor_breach_after_clean_baseline_fails(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 100.0], adapt=[(True, 0), (True, 2)]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "ESS-FLOOR REGRESSION" in proc.stdout

    def test_first_breach_reported_not_gated(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 99.0], adapt=[(True, 1), (True, 1)]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "below ESS floor (no clean baseline)" in proc.stdout


class TestBenchDiffRequestPlane:
    """The `request` manifest stanza (`hhmm_tpu/obs/request.py`) gates
    INVERTED on the same comparability key: fairness-spread or
    queue-share growth past the threshold is a request-plane
    regression (starvation creeping in / latency migrating into the
    queue)."""

    def test_spread_growth_fails(self, tmp_path):
        _write_fixture_rounds(
            tmp_path,
            [100.0, 100.0],
            request=[(10.0, 0.2), (25.0, 0.2)],
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "REQUEST-PLANE REGRESSION" in proc.stdout
        assert "fairness-spread" in proc.stdout

    def test_queue_share_growth_fails(self, tmp_path):
        _write_fixture_rounds(
            tmp_path,
            [100.0, 100.0],
            request=[(10.0, 0.2), (10.0, 0.5)],
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "queue-share" in proc.stdout

    def test_flat_observables_pass(self, tmp_path):
        _write_fixture_rounds(
            tmp_path,
            [100.0, 99.0],
            request=[(10.0, 0.2), (10.5, 0.21)],
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "request plane ok (2 observable(s))" in proc.stdout

    def test_noise_floor_baseline_never_gates(self, tmp_path):
        # a jitter-scale baseline (spread under 5 ms, queue share
        # under 0.05) cannot express meaningful relative growth:
        # +50% of noise is still noise, not a regression
        _write_fixture_rounds(
            tmp_path,
            [100.0, 100.0],
            request=[(2.0, 0.004), (3.0, 0.006)],
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "REQUEST-PLANE REGRESSION" not in proc.stdout

    def test_zero_baseline_never_gates(self, tmp_path):
        # a zero spread baseline cannot express relative growth: the
        # next record is reported, not gated (mirrors the zero-value
        # throughput rule)
        _write_fixture_rounds(
            tmp_path,
            [100.0, 100.0],
            request=[(0.0, 0.0), (50.0, 0.9)],
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "REQUEST-PLANE REGRESSION" not in proc.stdout

    def test_first_record_is_baseline(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0], request=[(10.0, 0.2)]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "request-plane baseline" in proc.stdout

    def test_unmeasured_middle_round_keeps_prior_baseline(self, tmp_path):
        # round 2's spread is unmeasured (None): round 3's measured
        # 10x spread must still gate against round 1's baseline — an
        # unmeasured round must not silently re-baseline starvation
        _write_fixture_rounds(
            tmp_path,
            [100.0, 100.0, 100.0],
            request=[(10.0, 0.2), (None, 0.2), (100.0, 0.2)],
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "fairness-spread" in proc.stdout


class TestObsReport:
    MANIFEST = os.path.join(FIXTURES, "obs_report_manifest.json")
    METRICS = os.path.join(FIXTURES, "obs_report_metrics.jsonl")

    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"), *argv],
            capture_output=True,
            text=True,
        )

    def test_renders_complete_dashboard_from_fixtures(self):
        proc = self._run(self.MANIFEST)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        out = proc.stdout
        for section in (
            "== run ==",
            "== spans",
            "== compile ==",
            "== memory ==",
            "== plan ==",
            "== convergence",
            "== serving ==",
            "== request timeline ==",
            "== slo ==",
        ):
            assert section in out, section
        # convergence trajectory rows + totals
        assert "rhat_max" in out and "ess_min" in out
        assert "total divergences" in out
        # serving health incl. staleness + drift
        assert "snapshot staleness" in out and "drift alarms: 3" in out
        # the PR 6 plan stanza, surfaced at last: mesh axes, chunk
        # rounding, resolved branch, idle-device rationale
        assert "mesh: chain:1 x series:2 x sp:3" in out
        assert "devices used 6/8" in out
        assert "requested 6, rounded" in out
        assert "time-parallel branch: scan" in out
        assert "2 devices idle" in out
        # the request plane: per-tenant decomposition + fairness
        assert "tenant0" in out and "tenant1" in out
        assert "p99 spread 1.9875 ms" in out
        assert "(+1 tenant(s) omitted" in out
        assert "warm device re-time update/b128" in out
        # the async flush pipeline: in-flight depth, the overlap duel
        # verdict, and the per-device fan-out table
        assert "== pipeline ==" in out
        assert "in-flight: depth 0 (peak 2), 14 flight(s) harvested" in out
        assert "queue share sync 33.6% -> async 0.9%" in out
        assert "0 parity mismatch(es) — OK" in out
        assert "replay overlap share: 77.9%" in out
        assert "blake2b8-mod over 2 device(s), 1 tick(s) deferred" in out
        # the storm fairness arms
        assert "skewed p99 spread 66.8182 ms vs balanced 2.3868 ms" in out
        # the adaptation plane: ladder counters, ESS table, verdict
        assert "== adaptation ==" in out
        assert "rejuvenations: 5" in out
        assert "ESS min (window): 1.99" in out
        assert "verdict: TRACKING" in out
        # SLO verdicts: the fixture has both a PASS and a FAIL check
        assert "PASS" in out and "FAIL" in out and "UNMET" in out

    def test_metrics_jsonl_override(self):
        proc = self._run(self.MANIFEST, "--metrics", self.METRICS)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "drift alarms: 3" in proc.stdout

    def test_unreadable_input_exit_2(self, tmp_path):
        proc = self._run(str(tmp_path / "nope.json"))
        assert proc.returncode == 2
        bad = tmp_path / "bad.json"
        bad.write_text("{torn")
        assert self._run(str(bad)).returncode == 2

    def test_never_imports_jax(self):
        """The dashboard must render on hosts without the pinned jax —
        asserted statically (no jax import anywhere in the script)."""
        import ast as _ast

        src = open(os.path.join(REPO, "scripts", "obs_report.py")).read()
        for node in _ast.walk(_ast.parse(src)):
            if isinstance(node, _ast.Import):
                assert not any(
                    a.name.split(".")[0] == "jax" for a in node.names
                )
            elif isinstance(node, _ast.ImportFrom):
                assert (node.module or "").split(".")[0] != "jax"


class TestCheckGuardsInvariant5:
    def test_repo_passes(self, check_guards_repo):
        proc = check_guards_repo  # one shared repo scan (conftest)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "monotonic clocks" in proc.stdout

    def _run_on(self, tmp_path):
        return subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "check_guards.py"),
                str(tmp_path),
            ],
            capture_output=True,
            text=True,
        )

    def test_raw_time_time_flagged(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "slow.py").write_text(
            "import time as _t\n\ndef f():\n    return _t.time()\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "_t.time()" in proc.stdout

    def test_raw_time_in_bench_flagged(self, tmp_path):
        (tmp_path / "hhmm_tpu").mkdir()
        (tmp_path / "bench.py").write_text(
            "import time\n\ndef f():\n    return time.time()\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "bench.py" in proc.stdout and "time.time()" in proc.stdout

    def test_unregistered_serve_jit_flagged(self, tmp_path):
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "fast.py").write_text(
            "import jax\n\nf = jax.jit(lambda x: x)\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "telemetry" in proc.stdout

    def test_from_jax_import_jit_flagged(self, tmp_path):
        # the bare-name spelling must trip invariant 5b too, or the
        # check is trivially evaded
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "fast.py").write_text(
            "from jax import jit\n\nf = jit(lambda x: x)\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "telemetry" in proc.stdout

    def test_install_listeners_alone_insufficient(self, tmp_path):
        # only register_jit attributes an entry point; the global
        # listener must not satisfy the serve-module invariant
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "fast.py").write_text(
            "import jax\n"
            "from hhmm_tpu.obs.telemetry import install_listeners\n\n"
            "install_listeners()\n"
            "f = jax.jit(lambda x: x)\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "telemetry" in proc.stdout

    def test_registered_serve_jit_passes(self, tmp_path):
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "fast.py").write_text(
            "import jax\n"
            "from hhmm_tpu.obs.telemetry import register_jit\n\n"
            "f = register_jit('fast', jax.jit(lambda x: x))\n"
        )
        proc = self._run_on(tmp_path)
        # the toy repo trips OTHER invariants (missing sampler modules);
        # the telemetry registration itself must be clean
        assert "telemetry" not in proc.stdout, proc.stdout

    def test_raw_time_in_scripts_flagged(self, tmp_path):
        # 5a covers scripts/: probe timings feed the measured crossover
        # table, so wall-clock skew there corrupts dispatch decisions
        (tmp_path / "hhmm_tpu").mkdir()
        scripts = tmp_path / "scripts"
        scripts.mkdir()
        (scripts / "tpu_toy_probe.py").write_text(
            "import time\n\ndef f():\n    return time.time()\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "scripts/tpu_toy_probe.py" in proc.stdout
        assert "time.time()" in proc.stdout

    def test_raw_time_in_bench_zoo_flagged(self, tmp_path):
        (tmp_path / "hhmm_tpu").mkdir()
        (tmp_path / "bench_zoo.py").write_text(
            "import time\n\ndef f():\n    return time.time()\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "bench_zoo.py" in proc.stdout


class TestCheckGuardsInvariant6:
    def _run_on(self, tmp_path):
        return subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "check_guards.py"),
                str(tmp_path),
            ],
            capture_output=True,
            text=True,
        )

    def test_private_registry_flagged(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "rogue.py").write_text(
            "from hhmm_tpu.obs.metrics import MetricsRegistry\n\n"
            "my_registry = MetricsRegistry()\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "private" in proc.stdout and "MetricsRegistry" in proc.stdout

    def test_shadow_counter_call_flagged(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "shadow.py").write_text(
            "def counter(name):\n"
            "    return None\n\n"
            "def emit():\n"
            "    counter('fit.divergences')\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "bare `counter(...)`" in proc.stdout

    def test_module_level_count_dict_flagged(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "adhoc.py").write_text("_divergence_counts = {}\n")
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "count store" in proc.stdout

    def test_shared_registry_usage_passes(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "good.py").write_text(
            "from hhmm_tpu.obs.metrics import counter, gauge\n\n"
            "def emit():\n"
            "    counter('fit.divergences', sampler='nuts').inc(2)\n"
            "    gauge('fit.interim.rhat_max', chunk='1').set(1.01)\n"
        )
        proc = self._run_on(tmp_path)
        # other invariants (missing sampler modules) still fire on the
        # toy repo; the metrics discipline itself must be clean
        assert "metrics" not in proc.stdout.lower() or "MetricsRegistry" not in (
            proc.stdout
        ), proc.stdout
        assert "bare `counter" not in proc.stdout
        assert "count store" not in proc.stdout

    def test_function_local_count_dicts_allowed(self, tmp_path):
        # algorithm state is not a metrics sink: only MODULE-level
        # count stores are flagged
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "algo.py").write_text(
            "def tally(xs):\n"
            "    counts = {}\n"
            "    for x in xs:\n"
            "        counts[x] = counts.get(x, 0) + 1\n"
            "    return counts\n"
        )
        proc = self._run_on(tmp_path)
        assert "count store" not in proc.stdout

    def test_repo_passes_invariant_6(self, check_guards_repo):
        proc = check_guards_repo  # one shared repo scan (conftest)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "one shared metrics plane" in proc.stdout
