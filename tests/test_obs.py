"""Observability subsystem tests (`hhmm_tpu/obs/`, `scripts/bench_diff.py`).

Covers the contracts the rest of the stack leans on:

- span nesting + aggregation determinism (injectable clock — the same
  event multiset must aggregate to the same table, percentiles by
  exact order statistic);
- the disabled-mode fast path (shared no-op singleton, nothing
  recorded, ``sync`` never blocks);
- compile-counter flatness on a re-jitted-twice toy kernel (warm calls
  add zero backend compiles; a new shape adds exactly one trace to the
  registered entry point's cache);
- `serve/metrics.py` routing its compile counter through the telemetry
  registry with the ``summary()`` schema unchanged;
- manifest round-trip + corrupt-file tolerance (`batch/cache.py`
  discipline: quarantine aside, read as miss);
- `scripts/bench_diff.py` pass/fail fixtures AND exit 0 over the
  checked-in BENCH_*.json trajectory;
- `scripts/check_guards.py` invariant 5 (raw ``time.time()`` and
  unregistered serve/bench jits are flagged; the repo passes).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hhmm_tpu.obs import manifest as obs_manifest
from hhmm_tpu.obs import telemetry, trace
from hhmm_tpu.obs.trace import Tracer, _NULL_SPAN

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _FakeClock:
    """Deterministic clock: +1.0 per read."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


class TestSpans:
    def test_nesting_paths_and_aggregate_determinism(self):
        def run_once():
            t = Tracer(clock=_FakeClock())
            t.enable()
            with t.span("outer"):
                with t.span("inner"):
                    pass
                with t.span("inner"):
                    pass
            return t

        t1, t2 = run_once(), run_once()
        evs = t1.events()
        # completion order: inner, inner, outer; nested paths recorded
        assert [e["name"] for e in evs] == ["inner", "inner", "outer"]
        assert [e["path"] for e in evs] == [
            "outer/inner",
            "outer/inner",
            "outer",
        ]
        agg1, agg2 = t1.aggregate(), t2.aggregate()
        assert agg1 == agg2  # fully deterministic under the fake clock
        assert agg1["inner"]["count"] == 2
        assert agg1["outer"]["count"] == 1
        # fake clock: every span body costs exactly one tick except
        # outer, which spans its children's reads too
        assert agg1["inner"]["total_s"] == pytest.approx(2.0)

    def test_percentiles_exact_order_statistic(self):
        clock = _FakeClock()
        t = Tracer(clock=clock)
        t.enable()
        # 100 spans with durations 1..100 (each __exit__ adds one extra
        # clock read inside _record? no — enter reads once, exit reads
        # once: duration == 1 tick unless we stretch it manually)
        for i in range(100):
            sp = t.span("s")
            sp.__enter__()
            clock.t += i  # stretch: durations 1, 2, ..., 100
            sp.__exit__(None, None, None)
        agg = t.aggregate()["s"]
        durs = sorted(e["dur_s"] for e in t.events())
        assert durs == [float(i) for i in range(1, 101)]
        assert agg["p50_ms"] == pytest.approx(50 * 1e3)
        assert agg["p99_ms"] == pytest.approx(99 * 1e3)
        assert agg["max_ms"] == pytest.approx(100 * 1e3)

    def test_disabled_fast_path_shared_singleton(self):
        t = Tracer()
        t.disable()
        assert t.span("a") is t.span("b") is _NULL_SPAN
        with t.span("a"):
            pass
        t.event("e")
        assert t.events() == []
        # sync on the null span is identity — never blocks, never touches jax
        obj = object()
        assert t.span("x").sync(obj) is obj

    def test_env_flag(self, monkeypatch):
        t = Tracer()
        monkeypatch.delenv("HHMM_TPU_TRACE", raising=False)
        assert not t.enabled()
        # the env read is cached (the disabled fast path must not pay
        # an os.environ lookup per span site): a mid-process change is
        # only seen through use_env()
        monkeypatch.setenv("HHMM_TPU_TRACE", "1")
        assert not t.enabled()
        t.use_env()
        assert t.enabled()
        monkeypatch.setenv("HHMM_TPU_TRACE", "0")
        t.use_env()
        assert not t.enabled()
        # every common falsy spelling DISABLES (a misread would flip
        # the samplers to blocking sync boundaries)
        for v in ("off", "OFF", "FALSE", "No", " 0 "):
            monkeypatch.setenv("HHMM_TPU_TRACE", v)
            t.use_env()
            assert not t.enabled(), v
        t.enable()  # explicit override beats the env
        assert t.enabled()

    def test_bounded_event_log_and_streaming_aggregate(self):
        # a traced serving host emits spans per tick indefinitely: the
        # raw event window is bounded, the aggregate stays exact on
        # count/total/max with a decimated percentile sample
        clock = _FakeClock()
        t = Tracer(clock=clock, max_events=16, sample_cap=8)
        t.enable()
        for i in range(100):
            sp = t.span("tick")
            sp.__enter__()
            clock.t += i  # durations 1, 2, ..., 100
            sp.__exit__(None, None, None)
        assert len(t.events()) == 16  # window, oldest evicted
        assert t.dropped() == 100 - 16
        agg = t.aggregate()["tick"]
        assert agg["count"] == 100  # exact despite eviction
        assert agg["total_s"] == pytest.approx(sum(range(1, 101)))
        assert agg["max_ms"] == pytest.approx(100 * 1e3)
        # percentiles come from the bounded stride sample — within it
        assert 0 < agg["p50_ms"] <= agg["p99_ms"] <= agg["max_ms"]
        # deterministic: an identical run aggregates identically
        clock2 = _FakeClock()
        t2 = Tracer(clock=clock2, max_events=16, sample_cap=8)
        t2.enable()
        for i in range(100):
            sp = t2.span("tick")
            sp.__enter__()
            clock2.t += i
            sp.__exit__(None, None, None)
        assert t2.aggregate() == t.aggregate()
        t.reset()
        assert t.events() == [] and t.dropped() == 0 and t.aggregate() == {}

    def test_traced_decorator_and_annotate(self):
        t = Tracer(clock=_FakeClock())
        t.enable()

        @t.traced("work")
        def f(x):
            return x + 1

        assert f(1) == 2
        with t.span("s") as sp:
            sp.annotate(K=4, branch="seq")
        evs = {e["name"]: e for e in t.events()}
        assert evs["work"]["dur_s"] > 0
        assert evs["s"]["meta"] == {"K": 4, "branch": "seq"}

    def test_jsonl_export_roundtrip(self, tmp_path):
        t = Tracer(clock=_FakeClock())
        t.enable()
        with t.span("a"):
            pass
        path = str(tmp_path / "spans.jsonl")
        n = t.export_jsonl(path)
        lines = [json.loads(line) for line in open(path)]
        assert n == len(lines) == 1
        assert lines[0]["name"] == "a"

    def test_thread_safety_independent_nesting(self):
        import threading

        t = Tracer()
        t.enable()
        errs = []

        def worker(name):
            try:
                for _ in range(50):
                    with t.span(name):
                        with t.span(name + ".in"):
                            pass
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [
            threading.Thread(target=worker, args=(f"w{i}",)) for i in range(4)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errs
        agg = t.aggregate()
        for i in range(4):
            assert agg[f"w{i}"]["count"] == 50
            assert agg[f"w{i}.in"]["count"] == 50
        # nesting never crossed threads: every inner path is its own parent's
        for e in t.events():
            if e["name"].endswith(".in"):
                assert e["path"] == e["name"].replace(".in", "") + "/" + e["name"]


class TestCompileTelemetry:
    def test_compile_counter_flat_on_warm_rejit(self):
        reg = telemetry.CompileRegistry()
        assert reg.install_listeners()
        try:
            f = reg.register_jit("toy", jax.jit(lambda x: x * 2 + 1))
            f(jnp.ones(4)).block_until_ready()
            c_warm = reg.backend_compiles()
            assert c_warm >= 1
            # warm replay, twice: the counter must be FLAT
            f(jnp.ones(4)).block_until_ready()
            f(jnp.ones(4)).block_until_ready()
            assert reg.backend_compiles() == c_warm
            assert reg.jit_cache_sizes()["toy"] == 1
            # a new shape is one new traced signature and >= 1 compile
            f(jnp.ones(8)).block_until_ready()
            assert reg.backend_compiles() > c_warm
            assert reg.jit_cache_sizes()["toy"] == 2
            secs = reg.compile_seconds()
            assert secs.get("backend_compile_duration", 0.0) > 0.0
        finally:
            reg.uninstall_listeners()

    def test_registry_holds_weakrefs_and_prunes_dead(self):
        reg = telemetry.CompileRegistry()
        f = reg.register_jit("gone", jax.jit(lambda x: x))
        f(jnp.ones(2)).block_until_ready()
        assert reg.jit_cache_sizes()["gone"] == 1
        del f
        import gc

        gc.collect()
        # all-dead names are pruned from reads, not reported 0 forever
        assert "gone" not in reg.jit_cache_sizes()
        # re-registering under the same name does not grow the ref list
        for _ in range(5):
            g = reg.register_jit("churn", jax.jit(lambda x: x + 1))
        g(jnp.ones(2)).block_until_ready()
        assert reg.jit_cache_sizes()["churn"] == 1

    def test_serve_metrics_routes_through_scope(self):
        from hhmm_tpu.serve.metrics import ServeMetrics

        m = ServeMetrics()
        m.set_compile_count(7)
        assert m.compile_count == 7
        # the registry sees the serving counter without knowing the class
        assert telemetry.scope_counts().get("serve.compile_count", 0) >= 7
        # summary schema keys unchanged (bench.py --serve / test_serve.py
        # consumers)
        s = m.summary()
        assert s["compile_count"] == 7
        assert set(s) == {
            "requests",
            "ticks",
            "flushes",
            "ticks_per_sec",
            "latency_p50_ms",
            "latency_p90_ms",
            "latency_p99_ms",
            "degraded_responses",
            "degraded_attaches",
            "superseded_responses",
            "compile_count",
        }

    def test_sample_memory_tolerant(self):
        # CPU backend hides memory_stats: must be {} (not an exception),
        # and the peak watermark stays a dict
        out = telemetry.sample_memory()
        assert isinstance(out, dict)
        assert isinstance(telemetry.peak_memory(), dict)


class TestDispatchSpans:
    def test_branch_recorded_in_span_table(self):
        from hhmm_tpu.kernels.dispatch import (
            ffbs_dispatch,
            forward_filter_dispatch,
        )

        K, T = 3, 16
        log_pi = jnp.log(jnp.full((K,), 1.0 / K))
        log_A = jnp.log(jnp.full((K, K), 1.0 / K))
        log_obs = jnp.zeros((T, K))
        trace.tracer.enable()
        base = trace.events()
        try:
            forward_filter_dispatch(log_pi, log_A, log_obs)
            forward_filter_dispatch(
                log_pi, log_A, log_obs, time_parallel=True
            )
            ffbs_dispatch(jax.random.PRNGKey(0), log_pi, log_A, log_obs)
            names = {e["name"] for e in trace.events()[len(base) :]}
        finally:
            trace.tracer.use_env()
            trace.reset()
        assert "kernels.dispatch.forward_filter[seq]" in names
        assert "kernels.dispatch.forward_filter[assoc]" in names
        assert "kernels.dispatch.ffbs[fused]" in names
        # the kernels themselves contribute spans nested under dispatch
        assert "kernels.forward_filter" in names
        assert "kernels.ffbs" in names


class TestManifest:
    def test_roundtrip_atomic(self, tmp_path):
        man = obs_manifest.collect_manifest(
            config={"series": 8, "T": 128}, seed=42
        )
        assert man["version"] == obs_manifest.MANIFEST_VERSION
        assert man["versions"]["jax"] == jax.__version__
        assert man["workload_digest"]
        assert man["backend"] == "cpu"
        path = str(tmp_path / "manifest.json")
        obs_manifest.write_manifest(path, man)
        man2 = obs_manifest.load_manifest(path)
        # round-trip through JSON: identity up to JSON-representable types
        assert man2 == json.loads(json.dumps(man, default=str))

    def test_workload_digest_tracks_config(self):
        m1 = obs_manifest.collect_manifest(config={"T": 128}, seed=1)
        m2 = obs_manifest.collect_manifest(config={"T": 128}, seed=1)
        m3 = obs_manifest.collect_manifest(config={"T": 256}, seed=1)
        assert m1["workload_digest"] == m2["workload_digest"]
        assert m1["workload_digest"] != m3["workload_digest"]

    def test_observability_flags_do_not_fork_workload_digest(self):
        """The bench_diff comparability key must be blind to output
        paths/profiler flags — otherwise adding --manifest-out in CI
        makes every record its own baseline and the gate fails open."""
        import argparse

        import bench

        def ns(**over):
            base = {
                "series": 256, "T": 1024, "sampler": "gibbs",
                "manifest_out": None, "profile": None,
            }
            base.update(over)
            return argparse.Namespace(**base)

        a1, a2 = ns(), ns(manifest_out="/tmp/m.json", profile="/tmp/prof")
        m1 = obs_manifest.collect_manifest(
            config=vars(a1), workload_config=bench.workload_config(a1)
        )
        m2 = obs_manifest.collect_manifest(
            config=vars(a2), workload_config=bench.workload_config(a2)
        )
        assert m1["workload_digest"] == m2["workload_digest"]
        a3 = ns(T=2048)  # a REAL workload change still forks the key
        m3 = obs_manifest.collect_manifest(
            config=vars(a3), workload_config=bench.workload_config(a3)
        )
        assert m1["workload_digest"] != m3["workload_digest"]

    def test_missing_and_corrupt_tolerated(self, tmp_path, capsys):
        assert obs_manifest.load_manifest(str(tmp_path / "nope.json")) is None
        bad = tmp_path / "torn.json"
        bad.write_bytes(b'{"version": 1, "half-writ')
        assert obs_manifest.load_manifest(str(bad)) is None
        # quarantined aside so a re-write under the same name works
        assert not bad.exists()
        assert (tmp_path / "torn.json.corrupt").exists()
        # a JSON file that isn't a manifest is corrupt too
        notman = tmp_path / "not_manifest.json"
        notman.write_text('{"hello": "world"}')
        assert obs_manifest.load_manifest(str(notman)) is None

    def test_manifest_stanza_compact(self):
        st = obs_manifest.manifest_stanza(config={"T": 64})
        assert "spans" not in st and "argv" not in st
        assert {"workload_digest", "span_count", "backend_compiles"} <= set(st)


def _run_bench_diff(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_diff.py"), *argv],
        capture_output=True,
        text=True,
    )


def _write_fixture_rounds(d, values, stamped=True, traced=None):
    for n, v in enumerate(values, start=1):
        rec = {
            "metric": "fixture_throughput",
            "value": v,
            "unit": "series/sec",
            "backend": "cpu",
        }
        if stamped:
            rec["manifest"] = {
                "workload_digest": "wfix",
                "device_kind": "cpu",
                "versions": {"jax": "0.0-test"},
                "trace_enabled": bool(traced[n - 1]) if traced else False,
            }
        (d / f"BENCH_r{n:02d}.json").write_text(
            json.dumps({"n": n, "rc": 0, "parsed": rec})
        )


class TestBenchDiff:
    def test_checked_in_trajectory_exits_zero(self):
        proc = _run_bench_diff("--dir", REPO)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        # a readable per-metric delta table
        assert "tayal_batched_posterior_throughput" in proc.stdout
        assert "Δ%" in proc.stdout

    def test_regression_fails(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 80.0])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "REGRESSION" in proc.stdout

    def test_within_threshold_passes(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 95.0])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "ok vs round" in proc.stdout

    def test_improvement_passes(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 140.0])
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout

    def test_unstamped_records_never_gate(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 10.0], stamped=False)
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "ungated" in proc.stdout

    def test_crashed_round_reported_not_fatal(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 99.0])
        (tmp_path / "BENCH_r03.json").write_text(
            json.dumps({"n": 3, "rc": 1, "tail": "Traceback ...", "parsed": None})
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert "CRASHED" in proc.stdout

    def test_threshold_flag(self, tmp_path):
        _write_fixture_rounds(tmp_path, [100.0, 95.0])
        proc = _run_bench_diff("--dir", str(tmp_path), "--threshold", "2")
        assert proc.returncode == 1, proc.stdout

    def test_trace_regime_never_gates_across(self, tmp_path):
        # a traced run pays sync + span overhead: it must not gate
        # against an untraced baseline of the same workload (each
        # regime is its own comparability key)
        _write_fixture_rounds(
            tmp_path, [100.0, 10.0], traced=[False, True]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 0, proc.stdout
        assert proc.stdout.count("baseline for its workload/stack key") == 2

    def test_trace_regime_gates_within(self, tmp_path):
        _write_fixture_rounds(
            tmp_path, [100.0, 80.0], traced=[True, True]
        )
        proc = _run_bench_diff("--dir", str(tmp_path))
        assert proc.returncode == 1, proc.stdout
        assert "REGRESSION" in proc.stdout


class TestCheckGuardsInvariant5:
    def test_repo_passes(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "check_guards.py")],
            capture_output=True,
            text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "monotonic clocks" in proc.stdout

    def _run_on(self, tmp_path):
        return subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "check_guards.py"),
                str(tmp_path),
            ],
            capture_output=True,
            text=True,
        )

    def test_raw_time_time_flagged(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "slow.py").write_text(
            "import time as _t\n\ndef f():\n    return _t.time()\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "_t.time()" in proc.stdout

    def test_raw_time_in_bench_flagged(self, tmp_path):
        (tmp_path / "hhmm_tpu").mkdir()
        (tmp_path / "bench.py").write_text(
            "import time\n\ndef f():\n    return time.time()\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "bench.py" in proc.stdout and "time.time()" in proc.stdout

    def test_unregistered_serve_jit_flagged(self, tmp_path):
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "fast.py").write_text(
            "import jax\n\nf = jax.jit(lambda x: x)\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "telemetry" in proc.stdout

    def test_from_jax_import_jit_flagged(self, tmp_path):
        # the bare-name spelling must trip invariant 5b too, or the
        # check is trivially evaded
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "fast.py").write_text(
            "from jax import jit\n\nf = jit(lambda x: x)\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "telemetry" in proc.stdout

    def test_install_listeners_alone_insufficient(self, tmp_path):
        # only register_jit attributes an entry point; the global
        # listener must not satisfy the serve-module invariant
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "fast.py").write_text(
            "import jax\n"
            "from hhmm_tpu.obs.telemetry import install_listeners\n\n"
            "install_listeners()\n"
            "f = jax.jit(lambda x: x)\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "telemetry" in proc.stdout

    def test_registered_serve_jit_passes(self, tmp_path):
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "fast.py").write_text(
            "import jax\n"
            "from hhmm_tpu.obs.telemetry import register_jit\n\n"
            "f = register_jit('fast', jax.jit(lambda x: x))\n"
        )
        proc = self._run_on(tmp_path)
        # the toy repo trips OTHER invariants (missing sampler modules);
        # the telemetry registration itself must be clean
        assert "telemetry" not in proc.stdout, proc.stdout
