"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual CPU mesh (the driver separately dry-runs the
multi-chip path via ``__graft_entry__.dryrun_multichip``).

NOTE: the environment's sitecustomize imports jax at interpreter start
with ``JAX_PLATFORMS=axon`` already captured by jax's config, so setting
the env var here is NOT enough — we must also update jax.config before
any backend is initialized.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json

import numpy as np
import pytest


@pytest.fixture(scope="session")
def check_guards_repo():
    """ONE full-repo `scripts/check_guards.py` run shared by every
    invariant acceptance test. Ten tests across nine modules each
    asserted a substring of the SAME no-argument full-scan output via
    their own subprocess — ~10 identical ~5 s scans on the tier-1
    duration budget (PR 12 discipline; the ledger guard measures the
    suite against an 800 s bar). Toy-tree runs keep their own
    subprocesses; only the no-argument repo scan is shared."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "check_guards.py")],
        capture_output=True,
        text=True,
        timeout=300,
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute tests (full apps, SBC suites, batch engines); "
        "`pytest -m 'not slow'` is the fast iteration subset (~13 min)",
    )


# ---- tier-1 duration ledger ----
#
# The tier-1 suite runs under a hard 870 s timeout (ROADMAP "Tier-1
# verify"); historically the only signal that the suite outgrew its
# budget was the timeout itself killing the run at N%. This ledger
# records every non-slow test's measured duration (setup + call +
# teardown) and persists it at session end, so the slow-marked
# headroom guard (`tests/test_durations.py`) can fail LOUDLY when the
# measured total crosses 800 s — before the 870 s ceiling is
# rediscovered by timeout. Persistence is guarded (`_should_persist`):
# only a CLEAN session (exitstatus 0) that exercised a meaningful
# slice of the suite — and at least ~80% of whatever the previous
# ledger covered — may replace the measurement. A one-file iteration
# run, an aborted/failed session, or a partial subset must not clobber
# the full ledger with an understated total the guard would then
# vacuously pass.

DURATIONS_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".tier1_durations.json",
)
_MIN_TESTS_TO_PERSIST = 100
_nonslow_durations = {}


def _should_persist(exitstatus, n_new, prev_n):
    """Whether a finished session may replace the duration ledger.
    Pure decision logic (unit-tested in `tests/test_durations.py`)."""
    if exitstatus != 0:
        return False  # aborted/failed run: totals are understated
    if n_new < _MIN_TESTS_TO_PERSIST:
        return False  # one-file iteration run
    if prev_n and n_new < 0.8 * prev_n:
        return False  # multi-file subset vs a fuller prior measurement
    return True


def pytest_runtest_logreport(report):
    if "slow" in report.keywords:
        return
    _nonslow_durations[report.nodeid] = (
        _nonslow_durations.get(report.nodeid, 0.0) + report.duration
    )


def pytest_sessionfinish(session, exitstatus):
    prev_n = 0
    try:
        with open(DURATIONS_PATH) as f:
            prev_n = int(json.load(f).get("n_tests", 0))
    except (OSError, ValueError):
        pass
    if not _should_persist(exitstatus, len(_nonslow_durations), prev_n):
        return
    ledger = {
        "total_s": round(sum(_nonslow_durations.values()), 3),
        "n_tests": len(_nonslow_durations),
        "tests": {
            k: round(v, 3) for k, v in _nonslow_durations.items()
        },
    }
    try:
        tmp = DURATIONS_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(ledger, f, indent=0, sort_keys=True)
        os.replace(tmp, DURATIONS_PATH)
    except OSError:
        pass  # a read-only checkout must not fail the suite


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def tayal_wf_tasks():
    """Shared synthetic walk-forward task list: 2 symbols x 4 days of
    simulated ticks, 2-day train + 1-day trade windows -> 4 tasks.
    Used by the wf_trade tests across sampler families."""
    from hhmm_tpu.apps.tayal import build_tasks, simulate_ticks

    rng = np.random.default_rng(11)
    days = {
        sym: [
            dict(
                zip(
                    ("price", "size", "t_seconds"),
                    simulate_ticks(rng, n_legs=60)[:3],
                )
            )
            for _ in range(4)
        ]
        for sym in ("AAA", "BBB")
    }
    tasks = build_tasks(days, train_days=2, trade_days=1)
    assert len(tasks) == 4  # 2 windows x 2 symbols
    return tasks
