"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual CPU mesh (the driver separately dry-runs the
multi-chip path via ``__graft_entry__.dryrun_multichip``).

NOTE: the environment's sitecustomize imports jax at interpreter start
with ``JAX_PLATFORMS=axon`` already captured by jax's config, so setting
the env var here is NOT enough — we must also update jax.config before
any backend is initialized.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute tests (full apps, SBC suites, batch engines); "
        "`pytest -m 'not slow'` is the fast iteration subset (~13 min)",
    )


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def tayal_wf_tasks():
    """Shared synthetic walk-forward task list: 2 symbols x 4 days of
    simulated ticks, 2-day train + 1-day trade windows -> 4 tasks.
    Used by the wf_trade tests across sampler families."""
    from hhmm_tpu.apps.tayal import build_tasks, simulate_ticks

    rng = np.random.default_rng(11)
    days = {
        sym: [
            dict(
                zip(
                    ("price", "size", "t_seconds"),
                    simulate_ticks(rng, n_legs=60)[:3],
                )
            )
            for _ in range(4)
        ]
        for sym in ("AAA", "BBB")
    }
    tasks = build_tasks(days, train_days=2, trade_days=1)
    assert len(tasks) == 4  # 2 windows x 2 symbols
    return tasks
