"""Regression gates for the real-data Tayal replication path.

These tests run the committed-artifact pipeline (RData load → zig-zag →
stan-gate decode → xts expansion → trading) on the REAL G.TO tick data
with the reference's PUBLISHED posterior means (main.pdf Table 8), so
the evidence behind `results/tayal_replication.json` cannot silently
rot. No MCMC: a single published-parameter draw decodes in well under a
second on CPU, keeping this in the `not slow` subset.
"""

import os

import numpy as np
import pytest

DATA = "/root/reference/tayal2009/data/G.TO"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(DATA), reason="reference tick data not present"
)

# published posterior means, main.pdf Table 8 (G.TO 2007-05-04..10)
PUB_PI1 = 0.51
PUB_A = [[0.46, 0.54], [0.09, 0.91]]
PUB_PHI = np.array([
    [0.01, 0.02, 0.01, 0.34, 0.22, 0.35, 0.03, 0.01, 0.02],
    [0.00, 0.02, 0.00, 0.05, 0.80, 0.02, 0.08, 0.00, 0.02],
    [0.01, 0.00, 0.03, 0.36, 0.20, 0.39, 0.00, 0.02, 0.00],
    [0.02, 0.00, 0.06, 0.02, 0.88, 0.01, 0.00, 0.01, 0.00],
])
# main.pdf Table 5, row 2007-05-11 (the Rmd window's own OOS day):
# [buy&hold, lag0..lag5] compound daily returns in percent
PUB_T5_0511 = [-0.04, 0.18, 0.10, 1.13, -0.50, -0.64, 0.29]


@pytest.fixture(scope="module")
def rmd_window():
    from hhmm_tpu.apps.rdata import load_tick_days_rdata
    from hhmm_tpu.apps.tayal.features import extract_features

    days = load_tick_days_rdata(DATA)[3:9]  # 05-04..10 ins, 05-11 oos
    price = np.concatenate([d["price"] for d in days])
    size = np.concatenate([d["size"] for d in days])
    t = np.concatenate([d["t_seconds"] for d in days])
    ins_end = sum(len(d["price"]) for d in days[:-1]) - 1
    zig = extract_features(price, size, t, alpha=0.25)
    return price, size, t, ins_end, zig


class TestRmdWindowParity:
    def test_leg_count_matches_published(self, rmd_window):
        """main.pdf §3.6.1: 'In-sample dataset reduced to 8386
        zig-zags' — a bit-level pin of the feature extraction on the
        real ticks."""
        price, size, t, ins_end, zig = rmd_window
        assert int((zig.end <= ins_end).sum()) == 8386

    def test_timestamp_duplication_is_material(self, rmd_window):
        """~43% of ticks share a timestamp — the xts-join look-ahead
        artifact is not a corner case on this data."""
        price, size, t, ins_end, zig = rmd_window
        frac = 1.0 - len(np.unique(t)) / len(t)
        assert 0.3 < frac < 0.6

    def test_sign_sequence_does_not_alternate(self, rmd_window):
        """~1/3 of adjacent legs share a sign (flat-gap legs,
        `feature-extraction.R:27-29`): the hard gate's strict
        alternation assumption fails on real ticks, which is why the
        replication path uses gate_mode='stan'."""
        price, size, t, ins_end, zig = rmd_window
        sign = (zig.feature > 9).astype(int)
        frac = float((sign[1:] == sign[:-1]).mean())
        assert 0.2 < frac < 0.45


class TestPublishedParamsDecode:
    @pytest.fixture(scope="class")
    def decoded(self, rmd_window):
        import jax.numpy as jnp
        from hhmm_tpu.apps.tayal.features import to_model_inputs
        from hhmm_tpu.apps.tayal.pipeline import classify_hard, label_and_trade
        from hhmm_tpu.models import TayalHHMMLite

        price, size, t, ins_end, zig = rmd_window
        model = TayalHHMMLite(gate_mode="stan")
        theta = model.pack(
            {
                "p_11": jnp.asarray(PUB_PI1),
                "A_row": jnp.asarray(PUB_A),
                "phi_k": jnp.asarray(PUB_PHI / PUB_PHI.sum(axis=1, keepdims=True)),
            }
        )[None, :]
        x, sign = to_model_inputs(zig.feature)
        n_ins = int((zig.end <= ins_end).sum())
        data = {
            "x": jnp.asarray(x[:n_ins]),
            "sign": jnp.asarray(sign[:n_ins]),
            "x_oos": jnp.asarray(x[n_ins:]),
            "sign_oos": jnp.asarray(sign[n_ins:]),
        }
        gen = model.generated(jnp.asarray(theta), data)
        leg_state = np.concatenate(
            [classify_hard(gen["alpha"]), classify_hard(gen["alpha_oos"])]
        )
        lags = (0, 1, 2, 3, 4, 5)
        lw_xts = label_and_trade(
            price, zig, leg_state, ins_end, lags, t_seconds=t, expansion="xts"
        )
        lw_pos = label_and_trade(
            price, zig, leg_state, ins_end, lags, expansion="positional"
        )
        return n_ins, lw_xts, lw_pos

    @staticmethod
    def _compound_pct(ret):
        return float((np.prod(1 + ret) - 1) * 100)

    def test_buy_and_hold_matches_published(self, decoded):
        _, lw, _ = decoded
        assert abs(self._compound_pct(lw.bnh) - PUB_T5_0511[0]) < 0.05

    def test_oos_switch_rate_band(self, decoded):
        n_ins, lw, _ = decoded
        top = lw.leg_topstate[n_ins:]
        switches = int((top[1:] != top[:-1]).sum())
        # published-params decode switches every ~2.2 legs (measured
        # 625 over 1380 OOS legs); a drift out of this band means the
        # filter or classification changed
        assert 500 <= switches <= 750

    def test_xts_advance_lifts_low_lags(self, decoded):
        """The timestamp-join expansion advances entries into the
        extremum bursts: same signals (equal trade counts), strictly
        better lag-0 compound return than the positional expansion
        (measured −0.71% vs −3.39% on 05-11)."""
        _, lw_xts, lw_pos = decoded
        assert len(lw_xts.trades[0]) == len(lw_pos.trades[0])
        lift = self._compound_pct(lw_xts.trades[0].ret) - self._compound_pct(
            lw_pos.trades[0].ret
        )
        assert lift > 1.0

    def test_low_lag_returns_near_published(self, decoded):
        """With the xts expansion the published-params decode lands
        within ~1% of the published Table 5 row at every lag (the
        residual is decode noise: published numbers come from 250
        posterior draws, this gate uses the posterior mean)."""
        _, lw, _ = decoded
        for lag in range(6):
            got = self._compound_pct(lw.trades[lag].ret)
            assert abs(got - PUB_T5_0511[1 + lag]) < 1.5, (lag, got)


class TestDegenerateModeEvidence:
    """Reference defect #8 (round 4): the soft gate's emission-only
    track must remain demonstrable on the real window — the structural
    fact behind the registered protocol's split headline
    (`docs/phi_protocol.md`). Deterministic: one FFBS decode per θ, no
    MCMC."""

    def test_emission_only_track_dominates_published_mode(self, rmd_window):
        """Three facts that pin the defect, all at fixed θ (no MCMC):

        1. Under the soft gate the PATH posterior rides the
           transition-free inconsistent track at ANY θ — even the
           published posterior-mean θ decodes mostly inconsistent
           (hard-gating the same θ forces consistency 1.0, at a
           catastrophic loglik on this non-alternating data — the
           known hard-gate invalidity).
        2. In θ-space, a maximally sign-AGNOSTIC θ (every state emits
           the pooled symbol frequencies — zero regime structure)
           out-scores the published θ by >100 nats on the model's own
           likelihood: the θ posterior is pulled away from the
           published configuration.
        3. The decode stays top-state-meaningful anyway: inconsistent
           destinations still belong to the correct bear/bull PAIR,
           which is why the trading tables replicate while the raw
           emission coordinates depend on sampler provenance."""
        import jax
        import jax.numpy as jnp

        from hhmm_tpu.apps.tayal.features import to_model_inputs
        from hhmm_tpu.apps.tayal.replication import degenerate_mode_probe
        from hhmm_tpu.models import TayalHHMMLite

        price, size, t, ins_end, zig = rmd_window
        x, sign = to_model_inputs(zig.feature)
        ins = zig.end <= ins_end
        n_ins = int(ins.sum())
        data = {"x": jnp.asarray(x[:n_ins]), "sign": jnp.asarray(sign[:n_ins])}
        model = TayalHHMMLite()

        # published-mode θ (main.pdf Table 8 means)
        pub = model.pack(
            {
                "p_11": jnp.asarray(PUB_PI1),
                "A_row": jnp.asarray(PUB_A, jnp.float32),
                "phi_k": jnp.asarray(PUB_PHI / PUB_PHI.sum(1, keepdims=True)),
            }
        )
        probe_pub = degenerate_mode_probe(model, pub, data, jax.random.PRNGKey(0))

        # sign-agnostic θ: every state emits the EMPIRICAL pooled symbol
        # frequencies — no regime structure at all
        freq = np.bincount(x[:n_ins], minlength=9) + 1.0
        freq = freq / freq.sum()
        agn = model.pack(
            {
                "p_11": jnp.asarray(0.5),
                "A_row": jnp.full((2, 2), 0.5),
                "phi_k": jnp.asarray(np.tile(freq, (4, 1)), jnp.float32),
            }
        )
        probe_agn = degenerate_mode_probe(model, agn, data, jax.random.PRNGKey(1))

        # fact 1: the free track dominates the path posterior at any θ
        assert probe_pub["path_sign_consistency"] < 0.5
        assert probe_agn["path_sign_consistency"] < 0.5
        hard = degenerate_mode_probe(
            TayalHHMMLite(gate_mode="hard"), pub, data, jax.random.PRNGKey(2)
        )
        assert hard["path_sign_consistency"] == 1.0
        assert hard["pure_loglik"] < probe_pub["pure_loglik"] - 10_000.0
        # fact 2: the defect in one inequality — no regime structure
        # beats the published structure on the model's own likelihood
        assert probe_agn["pure_loglik"] > probe_pub["pure_loglik"] + 100.0

    def test_registered_record_is_coherent(self):
        """The committed registered block: headline scope documented,
        Gibbs in the degenerate mode, investigation fields present."""
        import json

        path = os.path.join(
            os.path.dirname(__file__), "..", "results", "tayal_replication.json"
        )
        with open(path) as f:
            reg = json.load(f)["registered"]
        assert "basin" in reg["headline"]["scope"]
        assert reg["investigation"]["gibbs_mode_probe"]["path_sign_consistency"] < 0.5
        assert reg["gibbs_crosscheck"]["phi_45"] < 0.6  # degenerate mode
        assert 0.7 <= reg["headline"]["phi_45"] <= 0.95  # intended basin
