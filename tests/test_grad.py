"""Analytic forward-backward VJP (kernels/grad.py) vs XLA autodiff.

The custom VJP must agree with reverse-mode through the lax.scan forward
to f32 tolerance in every regime the model zoo produces: homogeneous and
time-varying transitions, ragged masks, and MASK_NEG-gated entries.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hhmm_tpu.core.lmath import MASK_NEG, log_normalize
from hhmm_tpu.kernels import forward_filter, forward_loglik


def _random_inputs(rng, T, K, time_varying=False, seed_shift=0):
    log_pi = log_normalize(jnp.asarray(rng.normal(size=(K,))))
    shape = (T - 1, K, K) if time_varying else (K, K)
    log_A = log_normalize(jnp.asarray(rng.normal(size=shape)), axis=-1)
    log_obs = jnp.asarray(rng.normal(size=(T, K)) - 1.0)
    return log_pi, log_A, log_obs


def _autodiff_loglik(log_pi, log_A, log_obs, mask=None):
    _, ll = forward_filter(log_pi, log_A, log_obs, mask)
    return ll


@pytest.mark.parametrize("time_varying", [False, True])
def test_value_matches_scan(rng, time_varying):
    log_pi, log_A, log_obs = _random_inputs(rng, 17, 3, time_varying)
    ll = forward_loglik(log_pi, log_A, log_obs)
    ll_ref = _autodiff_loglik(log_pi, log_A, log_obs)
    np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)


@pytest.mark.parametrize("time_varying", [False, True])
def test_grad_matches_autodiff(rng, time_varying):
    log_pi, log_A, log_obs = _random_inputs(rng, 17, 3, time_varying)
    g = jax.grad(forward_loglik, argnums=(0, 1, 2))(log_pi, log_A, log_obs)
    g_ref = jax.grad(_autodiff_loglik, argnums=(0, 1, 2))(log_pi, log_A, log_obs)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_grad_masked(rng):
    T, K = 21, 4
    log_pi, log_A, log_obs = _random_inputs(rng, T, K)
    mask = jnp.asarray((np.arange(T) < 13).astype(np.float32))
    g = jax.grad(forward_loglik, argnums=(0, 1, 2))(log_pi, log_A, log_obs, mask)
    g_ref = jax.grad(_autodiff_loglik, argnums=(0, 1, 2))(log_pi, log_A, log_obs, mask)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)
    # padding steps get exactly zero obs-gradient
    assert np.all(np.asarray(g[2])[13:] == 0.0)


def test_grad_gated_entries(rng):
    """MASK_NEG-gated transitions/emissions (Tayal hard gating) stay finite
    and match autodiff."""
    T, K = 15, 4
    log_pi, log_A, log_obs = _random_inputs(rng, T, K)
    log_A = log_A.at[0, 3].set(MASK_NEG).at[2, 1].set(MASK_NEG)
    log_obs = jnp.where(jnp.asarray(rng.random((T, K))) < 0.3, MASK_NEG, log_obs)
    g = jax.grad(forward_loglik, argnums=(0, 1, 2))(log_pi, log_A, log_obs)
    g_ref = jax.grad(_autodiff_loglik, argnums=(0, 1, 2))(log_pi, log_A, log_obs)
    for a, b in zip(g, g_ref):
        assert np.all(np.isfinite(np.asarray(a)))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_obs_grad_is_smoothed_marginal(rng):
    """The Baum-Welch identity itself: d loglik / d log_obs[t] = gamma[t]."""
    from hhmm_tpu.kernels import backward_pass, smooth

    log_pi, log_A, log_obs = _random_inputs(rng, 12, 3)
    g_obs = jax.grad(forward_loglik, argnums=2)(log_pi, log_A, log_obs)
    log_alpha, _ = forward_filter(log_pi, log_A, log_obs)
    gamma = jnp.exp(smooth(log_alpha, backward_pass(log_A, log_obs)))
    np.testing.assert_allclose(np.asarray(g_obs), np.asarray(gamma), rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_obs.sum(-1)), 1.0, rtol=1e-4)


def test_vmap_grad(rng):
    B, T, K = 5, 11, 3
    ins = [_random_inputs(np.random.default_rng(i), T, K) for i in range(B)]
    log_pi = jnp.stack([i[0] for i in ins])
    log_A = jnp.stack([i[1] for i in ins])
    log_obs = jnp.stack([i[2] for i in ins])

    def batched(lp, lA, lo):
        return jax.vmap(forward_loglik)(lp, lA, lo).sum()

    def batched_ref(lp, lA, lo):
        return jax.vmap(_autodiff_loglik)(lp, lA, lo).sum()

    g = jax.grad(batched, argnums=(0, 1, 2))(log_pi, log_A, log_obs)
    g_ref = jax.grad(batched_ref, argnums=(0, 1, 2))(log_pi, log_A, log_obs)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


@pytest.mark.slow
def test_model_logp_grad_unchanged(rng):
    """End-to-end: TayalHHMM make_logp gradient equals the pre-VJP path."""
    from hhmm_tpu.models import TayalHHMM

    model = TayalHHMM()
    T = 40
    x = jnp.asarray(rng.integers(0, 9, size=T))
    sign = jnp.asarray(np.arange(T) % 2)
    data = {"x": x, "sign": sign}
    theta = model.init_unconstrained(jax.random.PRNGKey(0), data)

    logp = model.make_logp(data)

    def logp_ref(th):
        params, ldj = model.unpack(th)
        log_pi, log_A, log_obs, mask = model.build(params, data)
        _, ll = forward_filter(log_pi, log_A, log_obs, mask)
        return ll + model.log_prior(params) + ldj

    np.testing.assert_allclose(float(logp(theta)), float(logp_ref(theta)), rtol=1e-6)
    g = jax.grad(logp)(theta)
    g_ref = jax.grad(logp_ref)(theta)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=2e-4, atol=1e-6)
