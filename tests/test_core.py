"""Tests for log-space math, distributions, and constraint bijectors."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from scipy import stats
from scipy.special import logsumexp as lse

from hhmm_tpu.core import lmath, dists
from hhmm_tpu.core.bijectors import (
    Identity,
    Positive,
    UnitInterval,
    Ordered,
    Simplex,
)


def test_log_vecmat_matvec(rng):
    K = 5
    x = rng.normal(size=K)
    A = rng.normal(size=(K, K))
    out = lmath.log_vecmat(jnp.asarray(x), jnp.asarray(A))
    expect = [lse(x + A[:, j]) for j in range(K)]
    np.testing.assert_allclose(out, expect, rtol=2e-4)
    out2 = lmath.log_matvec(jnp.asarray(A), jnp.asarray(x))
    expect2 = [lse(A[i] + x) for i in range(K)]
    np.testing.assert_allclose(out2, expect2, rtol=2e-4)


def test_normal_logpdf(rng):
    x = rng.normal(size=10)
    np.testing.assert_allclose(
        dists.normal_logpdf(jnp.asarray(x), 1.5, 2.0),
        stats.norm.logpdf(x, 1.5, 2.0),
        rtol=1e-4,
    )


def test_dirichlet_logpdf(rng):
    p = rng.dirichlet(np.ones(4))
    alpha = np.array([1.0, 2.0, 3.0, 0.5])
    np.testing.assert_allclose(
        dists.dirichlet_logpdf(jnp.asarray(p), jnp.asarray(alpha)),
        stats.dirichlet.logpdf(p, alpha),
        rtol=1e-4,
    )


def test_mixture_logpdf(rng):
    L = 3
    w = rng.dirichlet(np.ones(L))
    mu = rng.normal(size=L)
    sd = np.abs(rng.normal(size=L)) + 0.5
    x = rng.normal(size=7)
    got = dists.mixture_normal_logpdf(
        jnp.asarray(x), jnp.log(jnp.asarray(w)), jnp.asarray(mu), jnp.asarray(sd)
    )
    expect = lse(
        np.log(w)[None] + stats.norm.logpdf(x[:, None], mu[None], sd[None]), axis=1
    )
    np.testing.assert_allclose(got, expect, rtol=2e-4)


@pytest.mark.parametrize(
    "bij",
    [
        Identity(shape=(3,)),
        Positive(shape=(4,)),
        UnitInterval(shape=(2,)),
        Ordered(shape=(5,)),
        Ordered(shape=(2, 3)),
        Simplex(shape=(4,)),
        Simplex(shape=(3, 5)),
    ],
)
def test_bijector_roundtrip(rng, bij):
    x = rng.normal(size=bij.n_free)
    y, ldj = bij.forward(jnp.asarray(x))
    assert y.shape == bij.shape
    assert np.isfinite(ldj)
    x2 = bij.inverse(y)
    np.testing.assert_allclose(x2, x, rtol=1e-2, atol=2e-3)


def test_ordered_is_ordered(rng):
    bij = Ordered(shape=(6,))
    y, _ = bij.forward(jnp.asarray(rng.normal(size=6)))
    assert np.all(np.diff(np.asarray(y)) > 0)


def test_simplex_rows_sum_to_one(rng):
    bij = Simplex(shape=(3, 4))
    y, _ = bij.forward(jnp.asarray(rng.normal(size=bij.n_free)))
    np.testing.assert_allclose(np.sum(np.asarray(y), axis=-1), 1.0, rtol=1e-4)
    assert np.all(np.asarray(y) > 0)


@pytest.mark.parametrize(
    "bij",
    [Positive(shape=(3,)), UnitInterval(shape=(3,)), Ordered(shape=(4,)), Simplex(shape=(4,))],
)
def test_bijector_logdet_matches_autodiff(rng, bij):
    """log|J| from the bijector equals slogdet of the autodiff Jacobian."""
    x = jnp.asarray(rng.normal(size=bij.n_free))

    def fwd_flat(x_):
        y, _ = bij.forward(x_)
        y = y.reshape(-1)
        if isinstance(bij, Simplex):
            y = y[:-1]  # drop the redundant coordinate
        return y

    J = jax.jacfwd(fwd_flat)(x)
    _, expect = np.linalg.slogdet(np.asarray(J))
    _, got = bij.forward(x)
    np.testing.assert_allclose(got, expect, rtol=5e-4)


def test_simplex_uniform_sampling_is_dirichlet1():
    """Pushing N(0,large)≈flat draws through stick-breaking covers the simplex.

    Sanity check only: verify the transform hits all corners and stays
    normalized for extreme inputs.
    """
    bij = Simplex(shape=(3,))
    for scale in [0.1, 1.0, 10.0]:
        x = jnp.asarray(np.random.default_rng(1).normal(size=(2,)) * scale)
        y, ldj = bij.forward(x)
        assert np.isfinite(ldj)
        np.testing.assert_allclose(np.sum(np.asarray(y)), 1.0, rtol=2e-4)


class TestEssMany:
    def test_matches_scalar_ess(self):
        """ess_many == per-row ess across shapes, including AR(1)
        autocorrelation, near-constant rows, and odd draw counts."""
        from hhmm_tpu.infer.diagnostics import ess, ess_many

        rng = np.random.default_rng(0)
        rows = []
        for i in range(12):
            phi = [0.0, 0.5, 0.9, 0.99][i % 4]
            z = np.empty((2, 301))
            z[:, 0] = rng.normal(size=2)
            e = rng.normal(size=(2, 301))
            for t in range(1, 301):
                z[:, t] = phi * z[:, t - 1] + e[:, t]
            if i == 7:
                z[:] = 3.14  # constant row -> var_plus <= 0 branch
            rows.append(z)
        x = np.stack(rows)  # [12, 2, 301]
        got = ess_many(x, chunk=5)  # exercise chunking
        want = np.array([ess(x[i]) for i in range(len(x))])
        np.testing.assert_allclose(got, want, rtol=1e-10)

    def test_split_rhat_many_matches_scalar(self):
        from hhmm_tpu.infer.diagnostics import split_rhat, split_rhat_many

        rng = np.random.default_rng(1)
        x = rng.normal(size=(9, 2, 200))
        x[3] += np.array([0.0, 5.0])[:, None]  # divergent chain means
        x[5] = 2.0  # constant -> W <= 0 branch
        got = split_rhat_many(x)
        want = np.array([split_rhat(x[i]) for i in range(len(x))])
        np.testing.assert_allclose(got, want, rtol=1e-12)
