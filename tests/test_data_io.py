"""CSV data loaders (apps/data_io.py) — the stand-in for the
reference's quantmod downloads and per-day tick files."""

import numpy as np
import pytest

from hhmm_tpu.apps.data_io import load_ohlc_csv, load_tick_days, load_ticks_csv


@pytest.fixture
def ohlc_csv(tmp_path):
    p = tmp_path / "luv.csv"
    p.write_text(
        "Date,Open,High,Low,Close,Volume\n"
        "2005-01-03,16.0,16.5,15.8,16.2,1000\n"
        "2005-01-04,16.2,16.4,15.9,16.0,1200\n"
    )
    return str(p)


class TestOHLC:
    def test_roundtrip(self, ohlc_csv):
        ohlc = load_ohlc_csv(ohlc_csv)
        np.testing.assert_allclose(
            ohlc, [[16.0, 16.5, 15.8, 16.2], [16.2, 16.4, 15.9, 16.0]]
        )

    def test_feeds_make_dataset(self, ohlc_csv):
        from hhmm_tpu.apps.hassan.data import make_dataset

        ds = make_dataset(load_ohlc_csv(ohlc_csv), scale=False)
        np.testing.assert_allclose(ds.x, [16.0])
        np.testing.assert_allclose(ds.u, [[16.0, 16.5, 15.8, 16.2]])

    def test_high_below_low_rejected(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("open,high,low,close\n10,9,11,10\n")
        with pytest.raises(ValueError, match="high < low"):
            load_ohlc_csv(str(p))

    def test_missing_column(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("open,high,low\n1,2,3\n")
        with pytest.raises(ValueError, match="close"):
            load_ohlc_csv(str(p))

    def test_exact_name_beats_dotted_suffix(self, tmp_path):
        """An earlier 'adj.close' must not shadow the exact 'close'."""
        p = tmp_path / "adj.csv"
        p.write_text(
            "date,adj.close,open,high,low,close\n"
            "2005-01-03,15.0,16.0,16.5,15.8,16.2\n"
        )
        ohlc = load_ohlc_csv(str(p))
        assert ohlc[0, 3] == 16.2


class TestTicks:
    def test_hms_and_numeric_times(self, tmp_path):
        p = tmp_path / "t.csv"
        p.write_text(
            "time,price,size\n09:30:00,20.00,100\n09:30:01.5,20.01,50\n09:30:03,20.00,75\n"
        )
        d = load_ticks_csv(str(p))
        np.testing.assert_allclose(d["t_seconds"], [34200.0, 34201.5, 34203.0])
        np.testing.assert_allclose(d["price"], [20.0, 20.01, 20.0])
        p2 = tmp_path / "n.csv"
        p2.write_text("time,price,size\n0,20.0,1\n2.5,20.1,2\n")
        np.testing.assert_allclose(load_ticks_csv(str(p2))["t_seconds"], [0.0, 2.5])

    def test_unsorted_rejected(self, tmp_path):
        p = tmp_path / "t.csv"
        p.write_text("time,price,size\n5,20.0,1\n3,20.1,2\n")
        with pytest.raises(ValueError, match="not sorted"):
            load_ticks_csv(str(p))

    def test_day_directory(self, tmp_path):
        for day, px in (("2007.05.02", 20.0), ("2007.05.01", 19.0)):
            (tmp_path / f"G.TO.{day}.csv").write_text(
                f"time,price,size\n1,{px},10\n2,{px + 0.01},20\n"
            )
        days = load_tick_days(str(tmp_path), symbol="G.TO")
        assert len(days) == 2
        # ordered by embedded date, not listing order
        assert days[0]["price"][0] == 19.0
        assert days[1]["price"][0] == 20.0

    def test_day_directory_empty(self, tmp_path):
        with pytest.raises(ValueError, match="no matching"):
            load_tick_days(str(tmp_path))
