"""Tayal application tests: feature extraction (hand-built cases +
slow-oracle parity), trading rules, analytics, and the end-to-end
window pipeline / walk-forward harness on synthetic ticks."""

import numpy as np
import pytest

from hhmm_tpu.apps.tayal import (
    build_tasks,
    buyandhold,
    equity_curve,
    expand_to_ticks,
    expand_to_ticks_xts,
    extract_features,
    map_to_topstate,
    relabel_by_return,
    run_window,
    simulate_ticks,
    to_model_inputs,
    topstate_runs,
    topstate_summary,
    topstate_trading,
    wf_trade,
)
from hhmm_tpu.apps.tayal.constants import STATE_BEAR, STATE_BULL


def _slow_zigzag(price):
    """Literal transliteration of the reference's leg construction
    (`tayal2009/R/feature-extraction.R:19-36`) as an oracle."""
    T = len(price)
    direction = [0] * T
    for t in range(1, T):
        direction[t] = int(np.sign(price[t] - price[t - 1]))
    chg = [False] * T
    for t in range(1, T):
        chg[t] = direction[t] != 0 and direction[t] != direction[t - 1]
    cp = [t for t in range(T) if chg[t]]
    prices = [price[c - 1] for c in cp]
    starts = [0] + cp[:-1]
    ends = [c - 1 for c in cp[:-1]] + [T - 1]
    return np.array(prices), np.array(starts), np.array(ends)


class TestFeatures:
    def _ticks(self, seed=0, n_legs=120):
        rng = np.random.default_rng(seed)
        return simulate_ticks(rng, n_legs=n_legs)

    def test_zigzag_matches_slow_oracle(self):
        price, size, t, _ = self._ticks()
        zig = extract_features(price, size, t)
        p_o, s_o, e_o = _slow_zigzag(price)
        np.testing.assert_array_equal(zig.price, p_o)
        np.testing.assert_array_equal(zig.start, s_o)
        np.testing.assert_array_equal(zig.end, e_o)

    def test_legs_alternate_and_cover(self):
        price, size, t, _ = self._ticks(1)
        zig = extract_features(price, size, t)
        # f0 strictly alternates (zig-zag extrema alternate min/max)
        assert np.all(zig.f0[1:] != zig.f0[:-1])
        # spans tile the tick range without gaps
        assert zig.start[0] == 0 and zig.end[-1] == len(price) - 1
        np.testing.assert_array_equal(zig.start[1:], zig.end[:-1] + 1)

    def test_features_in_alphabet(self):
        price, size, t, _ = self._ticks(2)
        zig = extract_features(price, size, t)
        assert zig.feature.min() >= 1 and zig.feature.max() <= 18
        # up legs (ending at a max) get symbols 1..9, down legs 10..18
        up = zig.f0 == 1
        assert np.all(zig.feature[up] <= 9)
        assert np.all(zig.feature[~up] >= 10)

    def test_model_encoding(self):
        feature = np.array([1, 9, 10, 18, 5, 14])
        x, sign = to_model_inputs(feature)
        np.testing.assert_array_equal(sign, [0, 0, 1, 1, 0, 1])
        np.testing.assert_array_equal(x, [0, 8, 0, 8, 4, 4])

    def test_f1_trend_pattern(self):
        # strictly rising zig-zag: e1<e3<e5 and e2<e4 → trend up from leg 5
        price = []
        base = 10.0
        for i in range(10):
            leg = [base + 0.01 * j for j in range(3)] if i % 2 == 0 else [
                base + 0.02 - 0.01 * j for j in range(2)
            ]
            price.extend(leg)
            base += 0.015
        price = np.asarray(price)
        size = np.ones_like(price)
        t = np.arange(len(price), dtype=float)
        zig = extract_features(price, size, t)
        assert np.all(zig.f1[:4] == 0)
        assert np.all(zig.f1[4:] == 1)

    def test_volume_feature_responds(self):
        """A leg with a strong volume-per-second jump gets f2 != 0."""
        price, size, t, _ = self._ticks(3)
        zig = extract_features(price, size, t, alpha=0.25)
        assert np.any(zig.f2 != 0)

    def test_expand_to_ticks(self):
        price, size, t, _ = self._ticks(4)
        zig = extract_features(price, size, t)
        tick_vals = expand_to_ticks(zig.feature, zig, len(price))
        assert tick_vals.shape == (len(price),)
        for i in (0, len(zig) // 2, len(zig) - 1):
            np.testing.assert_array_equal(
                tick_vals[zig.start[i] : zig.end[i] + 1], zig.feature[i]
            )

    @staticmethod
    def _xts_expand_oracle(values, zig, t):
        """Literal transliteration of the reference's ``xts_expand``
        (`feature-extraction.R:1-5`): zig stamped at leg-end timestamps,
        zoo left-join with PAIRWISE duplicate matching (k-th tick at a
        timestamp matches the k-th stamp at it), na.locf backward then
        forward."""
        stamps = list(t[np.asarray(zig.end)])
        out = [None] * len(t)
        used = {}
        for u in range(len(t)):
            k = used.get(t[u], 0)
            # find the k-th stamp equal to t[u]
            seen = 0
            for m, s in enumerate(stamps):
                if s == t[u]:
                    if seen == k:
                        out[u] = values[m]
                        used[t[u]] = k + 1
                        break
                    seen += 1
        nxt = None
        for u in range(len(t) - 1, -1, -1):
            if out[u] is not None:
                nxt = out[u]
            elif nxt is not None:
                out[u] = nxt
        prev = None
        for u in range(len(t)):
            if out[u] is not None:
                prev = out[u]
            elif prev is not None:
                out[u] = prev
        return np.array(out)

    def test_expand_xts_equals_positional_without_duplicates(self):
        price, size, t, _ = self._ticks(5)
        zig = extract_features(price, size, t)
        assert len(np.unique(t)) == len(t)
        np.testing.assert_array_equal(
            expand_to_ticks_xts(zig.feature, zig, t),
            expand_to_ticks(zig.feature, zig, len(price)),
        )

    def test_expand_xts_matches_join_oracle_with_duplicates(self):
        price, size, t, _ = self._ticks(6, n_legs=60)
        # coarsen timestamps so ~half the ticks share a second, like the
        # real TSX series (~43% duplicated stamps)
        t = np.floor(t / 40.0) * 40.0
        zig = extract_features(price, size, t)
        got = expand_to_ticks_xts(zig.feature, zig, t)
        want = self._xts_expand_oracle(zig.feature, zig, t)
        np.testing.assert_array_equal(got, want)

    def test_expand_xts_advances_switch_into_burst(self):
        """A same-timestamp burst that spans a leg's ending extremum
        advances the next leg's values to just after the burst's first
        tick — the reference's look-ahead leak (main.pdf Tables 5/6
        depend on it at low lags; see docs/results.md)."""
        # zig-zag between 10 and 12: legs [0..2], [3..4], [5..6], ...
        price = np.array([10.0, 11.0, 12.0] + [11.0, 10.0, 11.0, 12.0] * 3 + [11.0, 10.0])
        size = np.ones_like(price)
        # ticks 1 and 2 share a timestamp: the burst contains the first
        # leg's ending extremum (tick 2)
        t = np.concatenate([[0.0, 1.0, 1.0], np.arange(2.0, len(price) - 1)])
        zig = extract_features(price, size, t)
        # leg 0 is the flat opening tick; leg 1 = [1..2] ends at the max
        np.testing.assert_array_equal(zig.start[:3], [0, 1, 3])
        np.testing.assert_array_equal(zig.end[:3], [0, 2, 4])
        vals = 10 * (1 + np.arange(len(zig)))
        pos = expand_to_ticks(vals, zig, len(price))
        xts = expand_to_ticks_xts(vals, zig, t)
        # leg 1's stamp (t=1.0 at its extremum tick 2) matches the FIRST
        # tick of the burst (tick 1); tick 2 backward-fills from the
        # NEXT stamp → the switch to leg 2's value lands one tick early
        np.testing.assert_array_equal(pos[:5], [10, 20, 20, 30, 30])
        np.testing.assert_array_equal(xts[:5], [10, 20, 30, 30, 30])
        # away from the burst the two expansions agree
        np.testing.assert_array_equal(pos[5:], xts[5:])


class TestTrading:
    def test_topstate_trading_hand_case(self):
        price = np.array([10.0, 11.0, 12.0, 11.0, 10.0, 9.0, 10.0, 11.0])
        top = np.array([1, 1, 1, -1, -1, -1, 1, 1])
        tr = topstate_trading(price, top, lag=0)
        # switches at ticks 3 (→bear) and 6 (→bull)
        np.testing.assert_array_equal(tr.signal, [3, 6])
        np.testing.assert_array_equal(tr.action, [-1, 1])
        np.testing.assert_array_equal(tr.start, [3, 6])
        np.testing.assert_array_equal(tr.end, [6, 7])
        # short 11→10: perchg −1/11, ret +1/11; long 10→11: +1/10
        np.testing.assert_allclose(tr.ret, [1 / 11, 1 / 10])

    def test_lag_shifts_entry(self):
        price = np.linspace(10, 12, 20)
        top = np.where(np.arange(20) < 10, 1, -1)
        tr0 = topstate_trading(price, top, lag=0)
        tr3 = topstate_trading(price, top, lag=3)
        assert tr3.start[0] == tr0.start[0] + 3

    def test_buyandhold(self):
        price = np.array([10.0, 11.0, 9.9])
        np.testing.assert_allclose(buyandhold(price), [0.1, -0.1])
        eq = equity_curve(buyandhold(price))
        np.testing.assert_allclose(eq[-1], 9.9 / 10.0)


class TestAnalytics:
    def test_runs_and_relabel(self):
        # legs: bull-ish states {2,3} first, then bear {0,1}, but prices
        # FALL in the first regime → ex-post relabel must swap
        leg_state = np.array([2, 3, 2, 0, 1, 0])
        starts = np.array([0, 3, 6, 9, 12, 15])
        ends = np.array([2, 5, 8, 11, 14, 17])
        price = np.concatenate([np.linspace(10, 8, 9), np.linspace(8, 10, 9)])
        top = map_to_topstate(leg_state)
        np.testing.assert_array_equal(
            top, [STATE_BULL] * 3 + [STATE_BEAR] * 3
        )
        runs = topstate_runs(top, starts, ends, price)
        assert len(runs) == 2
        run_top, leg_top, swapped = relabel_by_return(runs, top)
        assert swapped
        np.testing.assert_array_equal(run_top, [STATE_BEAR, STATE_BULL])
        summary = topstate_summary(
            type(runs)(topstate=run_top, start=runs.start, end=runs.end,
                       length=runs.length, ret=runs.ret)
        )
        assert summary["Bear"]["ret_mean"] < 0 < summary["Bull"]["ret_mean"]
        assert "Unconditional" in summary



@pytest.mark.slow
class TestPipeline:
    def test_window_end_to_end(self):
        """Synthetic ticks with planted regimes: the fitted window must
        recover the regime at materially better than chance."""
        rng = np.random.default_rng(7)
        price, size, t, leg_regime = simulate_ticks(rng, n_legs=500)
        from hhmm_tpu.infer import SamplerConfig

        res = run_window(
            price,
            size,
            t,
            ins_end_tick=int(0.8 * len(price)),
            config=SamplerConfig(num_warmup=200, num_samples=200, num_chains=1),
            gate_mode="hard",
        )
        assert res.stats["diverging"].mean() < 0.05
        assert set(np.unique(res.leg_topstate)) <= {STATE_BEAR, STATE_BULL}
        # align fitted legs with true per-leg regimes via leg starts
        zig = res.zig
        # true regime per tick
        true_leg_ends = None  # regimes were generated per simulated leg
        # compare at tick level using expand
        tick_top = expand_to_ticks(res.leg_topstate, zig, len(price))
        # reconstruct true tick-level regime from the simulator's legs
        # (approximately: regime changes align with direction runs)
        # use correlation with price drift as a weak but robust check:
        # bull-labeled ticks should have higher mean forward return
        fwd = np.diff(price) / price[:-1]
        bull = tick_top[:-1] == STATE_BULL
        assert fwd[bull].mean() > fwd[~bull].mean()
        # trading beats or ties buy-and-hold gross on this seed
        assert np.isfinite(res.trades[1].ret).all()
        assert "Unconditional" in res.summary

    def test_walk_forward(self, tmp_path, tayal_wf_tasks):
        tasks = tayal_wf_tasks
        from hhmm_tpu.infer import SamplerConfig

        results = wf_trade(
            tasks,
            config=SamplerConfig(num_warmup=100, num_samples=100, num_chains=1,
                                 max_treedepth=6),
            chunk_size=4,
            cache_dir=str(tmp_path),
        )
        assert len(results) == 4
        for r in results:
            assert r.diverged < 0.2
            assert set(r.trades.keys()) == {0, 1, 2, 3, 4, 5}
            assert np.isfinite(r.bnh).all()
        # second run hits the cache (same digest)
        results2 = wf_trade(
            tasks,
            config=SamplerConfig(num_warmup=100, num_samples=100, num_chains=1,
                                 max_treedepth=6),
            chunk_size=4,
            cache_dir=str(tmp_path),
        )
        np.testing.assert_array_equal(
            results[0].leg_topstate, results2[0].leg_topstate
        )

    def test_walk_forward_mesh_ragged(self, tayal_wf_tasks):
        """Length-sorted group fitting under a series mesh: the ragged
        final group must be repeat-padded to a device-divisible batch
        (round-3 regression: chunk % mesh series axis)."""
        import jax
        from jax.sharding import Mesh

        from hhmm_tpu.infer import SamplerConfig

        tasks = tayal_wf_tasks[:3]  # groups of 2 + 1 -> ragged final
        mesh = Mesh(np.array(jax.devices()[:2]), ("series",))
        results = wf_trade(
            tasks,
            config=SamplerConfig(num_warmup=40, num_samples=40, num_chains=1,
                                 max_treedepth=5),
            chunk_size=2,
            mesh=mesh,
        )
        assert len(results) == 3
        assert all(np.isfinite(r.bnh).all() for r in results)

    def test_walk_forward_warm_start(self, tayal_wf_tasks):
        """Pilot-seeded warm starts (the reference's stated Stan pain
        point, `hassan2005/main.Rmd:795`): runs end to end and yields
        valid trades; cold remains the default protocol."""
        from hhmm_tpu.infer import SamplerConfig

        cfg = SamplerConfig(num_warmup=60, num_samples=60, num_chains=2,
                            max_treedepth=5)
        phases = {}
        warm = wf_trade(
            tayal_wf_tasks, config=cfg, chunk_size=4, warm_start=True,
            phase_timings=phases,
        )
        assert len(warm) == len(tayal_wf_tasks)
        for r in warm:
            assert set(r.trades) == {0, 1, 2, 3, 4, 5}
            assert np.isfinite(r.bnh).all()
            assert r.diverged < 0.5
        # the profiling surface: every top-level phase present, plus the
        # round-5 decode sub-profile (prep / first-call-per-shape /
        # steady / cache IO and counts); sub-times account for the
        # decode total up to per-mark rounding
        assert {
            "features", "pilot_fit", "fit", "decode", "host_trading"
        } <= set(phases)
        assert all(v >= 0 for v in phases.values())
        assert phases["fit"] > 0
        sub = {k for k in phases if k.startswith("decode.")}
        assert {"decode.select", "decode.prep", "decode.first_call",
                "decode.host_reduce", "decode.cache_io",
                "decode.shapes_pending", "decode.dispatches"} <= sub
        assert phases["decode.dispatches"] >= 1
        sub_time = sum(
            phases[k] for k in sub
            if k not in ("decode.shapes_pending", "decode.dispatches")
        )
        # raw-float accumulation, one rounding per key: the sub-times
        # must account for the decode phase almost exactly
        assert sub_time <= phases["decode"] + 0.05 * len(sub)


class TestPerDrawRelabel:
    @pytest.mark.slow
    def test_matches_chainwise_analytics_per_draw(self):
        """`per_draw_relabel_stats` must reproduce, draw by draw, the
        numpy analytics chain (topstate_runs + relabel_by_return) run on
        the SAME FFBS path — the registered protocol's per-draw swap is
        exactly Tayal's ex-post rule, not an approximation of it."""
        import jax
        import jax.numpy as jnp

        from hhmm_tpu.apps.tayal.replication import per_draw_relabel_stats
        from hhmm_tpu.kernels.ffbs import backward_sample
        from hhmm_tpu.kernels.filtering import forward_filter
        from hhmm_tpu.models import TayalHHMMLite

        rng = np.random.default_rng(3)
        price, size, t, _ = simulate_ticks(rng, n_legs=220)
        zig = extract_features(price, size, t)
        x, sign = to_model_inputs(zig.feature)
        n_ins = len(zig) - 30
        data = {"x": jnp.asarray(x[:n_ins]), "sign": jnp.asarray(sign[:n_ins])}
        model = TayalHHMMLite(gate_mode="stan")

        # a handful of dispersed draws (random unconstrained points are
        # fine: the test is about the relabel rule, not the posterior)
        N = 6
        draws = np.stack(
            [
                np.asarray(model.init_unconstrained(k, data))
                for k in jax.random.split(jax.random.PRNGKey(5), N)
            ]
        )
        key = jax.random.PRNGKey(11)
        got = per_draw_relabel_stats(
            model, draws, data, zig.start[:n_ins], zig.end[:n_ins], price, key
        )

        # replay the identical FFBS keys and run the numpy analytics
        ks = jax.random.split(jax.random.fold_in(key, 0), N)
        for j in range(N):
            params, _ = model.unpack(jnp.asarray(draws[j]))
            log_pi, log_A, log_obs, _ = model.build(params, data)
            log_alpha, ll = forward_filter(log_pi, log_A, log_obs, None)
            z = np.asarray(backward_sample(ks[j], log_alpha, log_A, None))
            top = map_to_topstate(z)
            runs = topstate_runs(top, zig.start[:n_ins], zig.end[:n_ins], price)
            _, _, swapped = relabel_by_return(runs, top)
            assert bool(got["swapped"][j]) == bool(swapped), f"draw {j}"
            phi = np.asarray(params["phi_k"])
            if swapped:
                phi = phi[[3, 2, 1, 0], :]
            np.testing.assert_allclose(got["phi_45"][j], phi[3, 4], rtol=1e-5)
            np.testing.assert_allclose(got["phi_25"][j], phi[1, 4], rtol=1e-5)
            np.testing.assert_allclose(got["ll"][j], float(ll), rtol=1e-5)


class TestDeviceMedianDecode:
    @pytest.mark.slow
    def test_device_reduction_equals_host_median_argmax(self):
        """The wf decode's device-side median-α hard classification
        (shipped as [G, T] int32 instead of [G, D, T, K] f32 — the
        round-4 transfer optimization) must equal the host
        np.median/np.argmax reduction on the same generated output."""
        import jax
        import jax.numpy as jnp

        from hhmm_tpu.models import TayalHHMMLite

        rng = np.random.default_rng(4)
        model = TayalHHMMLite(gate_mode="stan")
        G, D, T, To = 3, 100, 96, 40
        data = {
            "x": jnp.asarray(rng.integers(0, 9, (G, T)), jnp.int32),
            "sign": jnp.asarray(rng.integers(0, 2, (G, T)), jnp.int32),
            "x_oos": jnp.asarray(rng.integers(0, 9, (G, To)), jnp.int32),
            "sign_oos": jnp.asarray(rng.integers(0, 2, (G, To)), jnp.int32),
        }
        samples = np.stack(
            [
                np.stack(
                    [
                        np.asarray(
                            model.init_unconstrained(
                                k, {kk: v[g] for kk, v in data.items()}
                            )
                        )
                        for k in jax.random.split(jax.random.PRNGKey(g), D)
                    ]
                )
                for g in range(G)
            ]
        )
        out = jax.vmap(model.generated)(jnp.asarray(samples), data)
        dev = np.asarray(jnp.argmax(jnp.median(out["alpha"], axis=1), axis=-1))
        host = np.stack(
            [
                np.argmax(np.median(np.asarray(out["alpha"])[g], axis=0), axis=-1)
                for g in range(G)
            ]
        )
        np.testing.assert_array_equal(dev, host)
