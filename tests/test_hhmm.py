"""HHMM structure DSL tests: validation, compiler correctness (hand
values + Tayal parity + empirical law of the recursive engine), and the
reference example trees."""

import numpy as np
import pytest

from hhmm_tpu.hhmm import (
    End,
    Internal,
    Production,
    compile_hhmm,
    fine1998_tree,
    finalize,
    gaussian_leaf_params,
    hhmm_sim,
    hmix_tree,
    jangmin2004_tree,
    leaf_groups,
    tayal_tree,
)
from hhmm_tpu.models import TayalHHMM


def _leaf(mu=0.0):
    return Production(obs=("gaussian", {"mu": mu, "sigma": 1.0}))


class TestValidation:
    def test_pi_must_sum_to_one(self):
        bad = Internal(pi=[0.5, 0.2], A=np.eye(2), children=[_leaf(), _leaf()])
        with pytest.raises(ValueError, match="sum to 1"):
            finalize(bad)

    def test_no_pi_mass_on_end(self):
        bad = Internal(
            pi=[0.5, 0.5], A=[[0.0, 1.0], [0.0, 1.0]], children=[_leaf(), End()]
        )
        with pytest.raises(ValueError, match="End child"):
            finalize(bad)

    def test_a_rows_stochastic(self):
        bad = Internal(
            pi=[1.0, 0.0], A=[[0.3, 0.3], [0.0, 1.0]], children=[_leaf(), End()]
        )
        with pytest.raises(ValueError, match="sums to"):
            finalize(bad)

    def test_orphanless_wiring(self):
        root = hmix_tree()
        comp = root.children[0]
        assert comp.parent is root and comp.index == 0
        for j, child in enumerate(comp.children):
            assert child.parent is comp and child.index == j

    def test_degenerate_end_only_subtree_rejected(self):
        inner = Internal(pi=[0.0], A=[[1.0]], children=[End()])
        # an End-only subtree cannot satisfy the pi-sums-to-1 constraint
        with pytest.raises(ValueError):
            finalize(Internal(pi=[1.0, 0.0], A=np.eye(2), children=[inner, End()]))

    def test_aliased_node_rejected(self):
        shared = Internal(
            pi=[1.0, 0.0],
            A=[[0.0, 1.0], [0.0, 1.0]],
            children=[_leaf(), End()],
        )
        root = Internal(
            pi=[0.5, 0.5],
            A=[[0.5, 0.5], [0.5, 0.5]],
            children=[shared, shared],
        )
        with pytest.raises(ValueError, match="more than once"):
            finalize(root)


class TestCompile:
    def test_hmix_hand_values(self):
        flat = compile_hhmm(hmix_tree())
        np.testing.assert_allclose(flat.pi, [0.5, 0.5])
        # from comp 2: 0.9 stay, 0.1 exit → root restart → re-enter 50/50
        np.testing.assert_allclose(flat.A, [[0.9, 0.1], [0.05, 0.95]])
        mu, sigma = gaussian_leaf_params(flat)
        np.testing.assert_allclose(mu, [5.0, -5.0])
        np.testing.assert_allclose(sigma, [1.0, 1.0])

    def test_tayal_matches_hand_derivation(self):
        """Compiled bull/bear tree == the hand-derived sparse K=4 HMM of
        `tayal2009/main.Rmd:306-345` as implemented in models/tayal.py."""
        rng = np.random.default_rng(3)
        p11, a_bear, a_bull = 0.37, 0.62, 0.81
        phi = rng.dirichlet(np.ones(9), size=4)
        flat = compile_hhmm(tayal_tree(p11, a_bear, a_bull, phi))

        model = TayalHHMM()
        # the reference parameterizes asymmetrically (`hhmm-tayal2009.stan:34-44`):
        # bear row carries the within-regime prob (A[0,1]=a01), bull row the
        # exit prob (A[2,0]=a20) — hence [a_bear, ...] but [1-a_bull, ...]
        params = {
            "p_11": np.array(p11),
            "A_row": np.array([[a_bear, 1 - a_bear], [1 - a_bull, a_bull]]),
            "phi_k": phi,
        }
        pi_ref, A_ref = model.assemble(params)
        np.testing.assert_allclose(flat.pi, np.asarray(pi_ref), atol=1e-12)
        np.testing.assert_allclose(flat.A, np.asarray(A_ref), atol=1e-12)
        np.testing.assert_array_equal(flat.groups, [0, 0, 1, 1])

    def test_fine1998_compiles(self):
        flat = compile_hhmm(fine1998_tree())
        assert flat.K == 5
        np.testing.assert_allclose(flat.A.sum(axis=1), np.ones(5), atol=1e-12)
        mu, _ = gaussian_leaf_params(flat)
        np.testing.assert_allclose(sorted(mu), [21.0, 32.0, 41.0, 42.0, 43.0])

    def test_jangmin_compiles(self):
        flat = compile_hhmm(jangmin2004_tree())
        assert flat.K == 63
        np.testing.assert_allclose(flat.A.sum(axis=1), np.ones(63), atol=1e-12)
        # top-state labels: 5 regimes, 15/15/15/15/3 leaves
        counts = np.bincount(flat.groups)
        np.testing.assert_array_equal(counts, [15, 15, 15, 15, 3])


class TestSimulatorMatchesCompiler:
    """The compiled flat HMM must be the exact law of the recursive
    engine: empirical leaf-transition frequencies from hhmm_sim ≈ A."""

    @pytest.mark.parametrize("tree_fn", [hmix_tree, fine1998_tree])
    def test_empirical_transitions(self, tree_fn):
        tree = tree_fn()
        flat = compile_hhmm(tree)
        rng = np.random.default_rng(0)
        T = 40000
        z, x = hhmm_sim(tree, T, rng)
        counts = np.zeros((flat.K, flat.K))
        np.add.at(counts, (z[:-1], z[1:]), 1.0)
        visited = counts.sum(axis=1) > 200
        emp = counts[visited] / counts[visited].sum(axis=1, keepdims=True)
        np.testing.assert_allclose(emp, flat.A[visited], atol=0.03)

    def test_emissions_match_leaves(self):
        tree = fine1998_tree()
        flat = compile_hhmm(tree)
        mu, _ = gaussian_leaf_params(flat)
        rng = np.random.default_rng(1)
        z, x = hhmm_sim(tree, 20000, rng)
        for k in range(flat.K):
            if (z == k).sum() > 100:
                assert abs(x[z == k].mean() - mu[k]) < 0.1

    def test_flat_hmm_sim_equivalence(self):
        """Sampling the compiled chain with the TPU-path simulator gives
        the same stationary occupancy as the recursive engine."""
        import jax

        from hhmm_tpu.sim import hmm_sim, obsmodel_gaussian

        tree = hmix_tree()
        flat = compile_hhmm(tree)
        mu, sigma = gaussian_leaf_params(flat)
        z_flat, _ = hmm_sim(
            jax.random.PRNGKey(0), 40000, flat.A, flat.pi, obsmodel_gaussian(mu, sigma)
        )
        z_rec, _ = hhmm_sim(tree, 40000, np.random.default_rng(2))
        occ_flat = np.bincount(np.asarray(z_flat), minlength=2) / 40000
        occ_rec = np.bincount(z_rec, minlength=2) / 40000
        # compare both to the analytic stationary distribution of A
        # (left eigenvector), not to each other — the sticky chain's
        # autocorrelation makes sim-vs-sim comparisons noisy
        evals, evecs = np.linalg.eig(flat.A.T)
        stat = np.real(evecs[:, np.argmax(np.real(evals))])
        stat = stat / stat.sum()
        np.testing.assert_allclose(occ_flat, stat, atol=0.03)
        np.testing.assert_allclose(occ_rec, stat, atol=0.03)



@pytest.mark.slow
class TestTreeToPosteriorRoundTrip:
    """End-to-end: tree DSL → recursive engine data → NUTS fit of the
    flat model → state recovery (the reference's simulate→fit→diagnose
    loop, `tayal2009/main-sim.R`, with the tree as the generator)."""

    def test_tayal_tree_fit_recovery(self):
        import jax
        import jax.numpy as jnp

        from hhmm_tpu.hhmm import hhmm_sim, tayal_tree
        from hhmm_tpu.infer import (
            SamplerConfig,
            apply_relabel,
            greedy_relabel,
            sample_nuts,
        )

        phi_true = np.array(
            [
                [0.5, 0.3, 0.2, 0, 0, 0, 0, 0, 0],
                [0, 0, 0, 0.6, 0.3, 0.1, 0, 0, 0],
                [0, 0, 0, 0.1, 0.3, 0.6, 0, 0, 0],
                [0, 0, 0, 0, 0, 0, 0.2, 0.3, 0.5],
            ]
        )
        tree = tayal_tree(0.5, 0.8, 0.65, phi_true)
        z, x = hhmm_sim(tree, 2000, np.random.default_rng(0))
        sign = np.where((z == 1) | (z == 2), 0, 1).astype(np.int32)
        data = {"x": jnp.asarray(x.astype(np.int32)), "sign": jnp.asarray(sign)}

        model = TayalHHMM(gate_mode="hard")
        cfg = SamplerConfig(num_warmup=300, num_samples=300, num_chains=2)
        init = jnp.stack(
            [
                model.init_unconstrained(k, data)
                for k in jax.random.split(jax.random.PRNGKey(1), 2)
            ]
        )
        qs, stats = sample_nuts(model.make_logp(data), jax.random.PRNGKey(2), init, cfg)
        assert float(np.asarray(stats["diverging"]).mean()) < 0.05
        gen = model.generated(qs.reshape(-1, qs.shape[-1])[::50], data)
        alpha_med = np.median(np.asarray(gen["alpha"]), axis=0)
        z_hat = np.argmax(alpha_med, axis=-1)
        z_rel = apply_relabel(z_hat, greedy_relabel(z, z_hat, 4))
        assert (z_rel == z).mean() > 0.9


class TestGroups:
    def test_depth2_groups(self):
        tree = fine1998_tree()
        g1 = leaf_groups(tree, depth=1)
        # leaves in DFS order: p21 (under q21), then q22 subtree
        assert g1[0] == 0
        assert all(g == 1 for g in g1[1:])
