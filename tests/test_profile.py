"""Kernel cost plane tests (`hhmm_tpu/obs/profile.py`,
`kernels/dispatch.py` DB integration, `serve/scheduler.py` sampled
flush profiling, `scripts/bench_diff.py` device-time gating,
`scripts/check_guards.py` invariant 9, `scripts/obs_report.py` cost
section).

The contracts pinned here:

- ``device_time``: warmup/compile split (the compile call never
  pollutes the rep statistics), exact-order-statistic p50 within
  [min, max], fresh ``arg_sets`` consumed per rep;
- ``cost_analysis``: real FLOPs where XLA reports them, ``{}`` (never
  an exception) where it doesn't — a timing-only row, not a dead
  sweep;
- the cost DB: atomic roundtrip, corrupt-file quarantine (torn DB →
  empty + ``.corrupt`` aside, dispatch falls back to the table),
  branch arbitration only within one (B, dtype, jax) stamp with the
  largest batch deciding;
- dispatch: a populated DB row for the CURRENT device kind flips
  ``"auto"`` (the ISSUE acceptance test), a row stamped with a foreign
  device kind does not, and explicit ``time_parallel=`` / plan scopes
  still outrank the DB;
- sampled flush profiling: re-timing the warm dispatched kernel adds
  ZERO compiles and only runs with the tracer on;
- bench_diff: a grown p50 between comparable records fails at the
  throughput threshold (inverted sign); unmeasured rows ride ungated;
- invariant 9: raw perf_counter-around-block_until_ready loops under
  ``hhmm_tpu/`` are flagged, per-iteration attribution and the
  harness itself are not.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hhmm_tpu.kernels import dispatch as kdispatch
from hhmm_tpu.obs import metrics as obs_metrics
from hhmm_tpu.obs import profile as obs_profile
from hhmm_tpu.obs import trace
from hhmm_tpu.obs.profile import DeviceTiming, KernelCostDB

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")


def _timing(p50: float, reps: int = 3) -> DeviceTiming:
    return DeviceTiming(
        reps=reps, mean_s=p50, p50_s=p50, min_s=p50, max_s=p50, compile_s=None
    )


@pytest.fixture
def scratch_db(tmp_path):
    """A scratch cost DB bound as the active dispatch source; always
    unbound afterwards so no other test sees injected winners."""
    db = KernelCostDB(str(tmp_path / "kernel_costs.json"))
    try:
        yield db
    finally:
        obs_profile.set_db(None)


class TestDeviceTime:
    def test_warmup_split_and_order_statistics(self):
        fn = jax.jit(lambda x: x * 2.0)
        x = jnp.arange(64.0)
        t = obs_profile.device_time(fn, x, reps=5)
        assert t.reps == 5
        assert t.compile_s is not None and t.compile_s > 0
        assert 0 < t.min_s <= t.p50_s <= t.max_s
        assert t.min_s <= t.mean_s <= t.max_s
        # the compile call is excluded from the rep statistics: a warm
        # re-execution of this kernel cannot plausibly cost as much as
        # its compile
        assert t.max_s < t.compile_s * 100  # sanity, not a tight bound
        d = t.to_json()
        assert set(d) == {"reps", "mean_s", "p50_s", "min_s", "max_s", "compile_s"}

    def test_no_warmup_reports_no_compile(self):
        fn = jax.jit(lambda x: x + 1.0)
        x = jnp.arange(8.0)
        jax.block_until_ready(fn(x))  # compile outside
        t = obs_profile.device_time(fn, x, reps=2, warmup=False)
        assert t.compile_s is None
        assert t.reps == 2

    def test_arg_sets_fresh_inputs_probe_convention(self):
        """reps+1 sets: compile on the LAST, timed reps cycle the
        rest — the tpu_*_probe.py convention."""
        seen = []
        fn = jax.jit(lambda x: x.sum())

        def spy(x):
            seen.append(int(x[0]))
            return fn(x)

        sets = [(jnp.full((4,), float(i)),) for i in range(4)]
        t = obs_profile.device_time(spy, arg_sets=sets, reps=3)
        assert t.reps == 3
        assert seen[0] == 3  # warmup on set -1
        assert seen[1:] == [0, 1, 2]  # timed reps on the fresh sets

    def test_reps_validation(self):
        with pytest.raises(ValueError):
            obs_profile.device_time(lambda: None, reps=0)
        with pytest.raises(ValueError):
            obs_profile.device_time(lambda: None, arg_sets=[])


class TestCostAnalysis:
    def test_matmul_reports_flops(self):
        a = jnp.ones((16, 16))
        cost = obs_profile.cost_analysis(lambda x, y: x @ y, a, a)
        if not cost:  # backend without a cost model: timing-only is legal
            pytest.skip("backend reports no cost analysis")
        assert cost["flops"] and cost["flops"] >= 16 * 16 * 16

    def test_failure_degrades_to_empty(self):
        # an un-lowerable call must yield {}, never raise — the row
        # degrades to timing-only
        assert obs_profile.cost_analysis(lambda x: x.nope(), object()) == {}


class TestRoofline:
    def test_known_fraction(self):
        r = obs_profile.roofline({"flops": 1e9}, 1.0, "cpu")
        assert r is not None
        assert r["flops_frac"] == pytest.approx(
            1e9 / obs_profile.PEAKS["cpu"]["flops_per_s"]
        )
        assert r["bytes_frac"] is None

    def test_none_tolerant(self):
        assert obs_profile.roofline(None, 1.0, "cpu") is None
        assert obs_profile.roofline({}, 1.0, "cpu") is None
        assert obs_profile.roofline({"flops": 1e9}, 0.0, "cpu") is None
        assert obs_profile.roofline({"flops": 1e9}, 1.0, None) is None
        assert obs_profile.roofline({"flops": 1e9}, 1.0, "TPU vFuture") is None


class TestKernelCostDB:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "kc.json")
        db = KernelCostDB(path)
        row = db.put_row(
            kernel="filter", branch="seq", K=4, T=128, B=8, dtype="float32",
            timing=_timing(1e-3), cost={"flops": 100.0},
            source="test",
        )
        db.save()
        db2 = KernelCostDB(path).load()
        assert db2.rows() == {row["key"]: row}
        # the stamp fields ride along (the manifest discipline)
        assert row["jax"] == jax.__version__
        assert row["device_kind"] == jax.devices()[0].device_kind

    def test_corrupt_quarantined(self, tmp_path, capsys):
        path = str(tmp_path / "kc.json")
        with open(path, "w") as f:
            f.write('{"version": 1, "rows": {"torn')
        db = KernelCostDB(path).load()
        assert db.rows() == {}
        assert os.path.exists(path + ".corrupt")
        assert not os.path.exists(path)
        # a re-save under the same name works (quarantine moved it aside)
        db.put_row(
            kernel="filter", branch="seq", K=2, T=64, B=1, dtype="float32",
            timing=_timing(1e-3),
        )
        db.save()
        assert KernelCostDB(path).load().rows()

    def test_winner_same_stamp_largest_batch(self):
        db = KernelCostDB("/nonexistent/unused.json")
        db._loaded = True  # in-memory only
        kw = dict(kernel="filter", K=4, T=256, dtype="float32", device_kind="x")
        # B=1: assoc wins; B=64: seq wins -> the batched pair decides
        db.put_row(branch="seq", B=1, timing=_timing(2e-3), **kw)
        db.put_row(branch="assoc", B=1, timing=_timing(1e-3), **kw)
        db.put_row(branch="seq", B=64, timing=_timing(1e-3), **kw)
        db.put_row(branch="assoc", B=64, timing=_timing(3e-3), **kw)
        assert db.winner("filter", 4, 256, "x") == "seq"

    def test_winner_prefers_newest_measurement_not_jax_string(self):
        """A re-probe after a jax upgrade must outrank the obsolete
        pair: arbitration ties on B break by row ``ts``, never by the
        jax version STRING ("0.4.9" > "0.4.30" lexicographically)."""
        db = KernelCostDB("/nonexistent/unused.json")
        db._loaded = True

        def row(branch, p50, jaxv, ts):
            return {
                "kernel": "filter", "branch": branch, "K": 4, "T": 256,
                "B": 64, "dtype": "float32", "device_kind": "x",
                "jax": jaxv, "timing": {"p50_s": p50}, "ts": ts,
            }

        db._rows = {
            "old-seq": row("seq", 1e-3, "0.4.9", "2025-01-01 00:00:00"),
            "old-assoc": row("assoc", 2e-3, "0.4.9", "2025-01-01 00:00:00"),
            "new-seq": row("seq", 2e-3, "0.4.30", "2026-08-01 00:00:00"),
            "new-assoc": row("assoc", 1e-3, "0.4.30", "2026-08-01 00:00:00"),
        }
        assert db.winner("filter", 4, 256, "x") == "assoc"

    def test_winner_needs_complete_pair_and_finite_timing(self):
        db = KernelCostDB("/nonexistent/unused.json")
        db._loaded = True
        kw = dict(kernel="filter", K=4, T=256, dtype="float32", device_kind="x")
        db.put_row(branch="seq", B=8, timing=_timing(1e-3), **kw)
        assert db.winner("filter", 4, 256, "x") is None  # no assoc row
        db.put_row(branch="assoc", B=8, timing=None, **kw)  # unmeasured
        assert db.winner("filter", 4, 256, "x") is None
        assert db.winner("filter", 4, 256, None) is None
        assert db.winner("filter", 4, 999, "x") is None  # wrong T


class TestDispatchDBIntegration:
    def _seed(self, db, K, T, seq_ms, assoc_ms, device_kind=None, kernel="filter"):
        dk = device_kind if device_kind is not None else kdispatch._device_kind()
        db.put_row(
            kernel=kernel, branch="seq", K=K, T=T, B=8, dtype="float32",
            timing=_timing(seq_ms * 1e-3), device_kind=dk,
        )
        db.put_row(
            kernel=kernel, branch="assoc", K=K, T=T, B=8, dtype="float32",
            timing=_timing(assoc_ms * 1e-3), device_kind=dk,
        )

    def test_db_row_flips_auto(self, scratch_db):
        """THE acceptance test: with no DB the empty CPU table says
        seq; an injected DB row for the current device kind flips
        "auto" to assoc at exactly that (K, T)."""
        assert kdispatch.use_assoc(3, 999) is False
        self._seed(scratch_db, 3, 999, seq_ms=1.0, assoc_ms=0.5)
        obs_profile.set_db(scratch_db)
        assert kdispatch.use_assoc(3, 999) is True
        assert kdispatch.resolve_auto(3, 999) == ("assoc", "db")
        # a seq-winning row is also DB-backed, not a table fallthrough
        self._seed(scratch_db, 3, 1000, seq_ms=0.5, assoc_ms=1.0)
        assert kdispatch.resolve_auto(3, 1000) == ("seq", "db")
        # neighbouring unmeasured points stay on the (empty) table
        assert kdispatch.resolve_auto(3, 998)[1] in ("table", "default")
        assert kdispatch.use_assoc(3, 998) is False

    def test_device_kind_mismatch_falls_back(self, scratch_db):
        self._seed(
            scratch_db, 3, 999, seq_ms=1.0, assoc_ms=0.5,
            device_kind="TPU vImaginary",
        )
        obs_profile.set_db(scratch_db)
        branch, source = kdispatch.resolve_auto(3, 999)
        assert branch == "seq" and source != "db"

    def test_explicit_and_plan_override_db(self, scratch_db):
        self._seed(scratch_db, 3, 999, seq_ms=1.0, assoc_ms=0.5)
        obs_profile.set_db(scratch_db)
        assert kdispatch.use_assoc(3, 999, time_parallel=False) is False
        with kdispatch.plan_time_parallel(False):
            assert kdispatch.use_assoc(3, 999) is False
            assert kdispatch.resolve_auto(3, 999) == ("seq", "plan")
        assert kdispatch.use_assoc(3, 999) is True  # scope popped

    def test_kernel_needs_its_own_rows(self, scratch_db):
        """A kernel resolves ONLY from its own measured rows: a
        filter-pair assoc win must never route viterbi/ffbs onto assoc
        unmeasured (the per-draw [T-1,K,K] materialization bet the
        both-kernels crossover rule forbids)."""
        self._seed(scratch_db, 3, 999, seq_ms=1.0, assoc_ms=0.5)
        obs_profile.set_db(scratch_db)
        assert kdispatch.resolve_auto(3, 999, kernel="filter") == ("assoc", "db")
        assert kdispatch.resolve_auto(3, 999, kernel="ffbs") == ("seq", "default")
        assert kdispatch.resolve_auto(3, 999, kernel="viterbi") == (
            "seq", "default",
        )
        # with its own rows the kernel is DB-backed like any other
        self._seed(scratch_db, 3, 999, seq_ms=0.5, assoc_ms=1.0, kernel="ffbs")
        assert kdispatch.resolve_auto(3, 999, kernel="ffbs") == ("seq", "db")

    def test_plan_branch_needs_all_decode_families(self, scratch_db):
        """The planner's branch is ONE pin spread over every kernel in
        its dispatch scope, so it must stay conservative: assoc only
        when EVERY family the pin governs (filter, viterbi, ffbs)
        resolves assoc — a partial win (even filter+viterbi with ffbs
        measured seq) leaves the plan on scan."""
        from hhmm_tpu.plan import WorkloadShape, make_plan

        self._seed(scratch_db, 3, 999, seq_ms=1.0, assoc_ms=0.5)  # filter only
        obs_profile.set_db(scratch_db)
        shape = WorkloadShape(B=4, T=999, C=1, K=3)
        assert make_plan(shape, n_devices=1).branch == "scan"
        self._seed(
            scratch_db, 3, 999, seq_ms=1.0, assoc_ms=0.5, kernel="viterbi"
        )
        # ffbs's own rows say seq: the pin must NOT route it to assoc
        self._seed(scratch_db, 3, 999, seq_ms=0.5, assoc_ms=1.0, kernel="ffbs")
        assert make_plan(shape, n_devices=1).branch == "scan"
        self._seed(scratch_db, 3, 999, seq_ms=1.0, assoc_ms=0.5, kernel="ffbs")
        assert make_plan(shape, n_devices=1).branch == "assoc"

    def test_refresh_rereads_disk(self, scratch_db):
        self._seed(scratch_db, 3, 999, seq_ms=1.0, assoc_ms=0.5)
        scratch_db.save()
        obs_profile.set_db(scratch_db.path)
        assert kdispatch.use_assoc(3, 999) is True
        # another process rewrites the DB: refresh() must pick it up
        db2 = KernelCostDB(scratch_db.path).load()
        self._seed(db2, 3, 999, seq_ms=0.5, assoc_ms=1.0)
        db2.save()
        obs_profile.refresh()
        assert kdispatch.use_assoc(3, 999) is False


class TestNWayArbitration:
    """Regression for the two-way-winner-pair assumption: `winner` /
    `resolve_auto` arbitrate N-way across EVERY measured branch of one
    kernel's largest comparable batch group — the three-way
    (seq/assoc/pallas) case a TPU probe run produces."""

    def _put(self, db, branch, ms, K=3, T=999, B=8, kernel="filter", dk=None):
        db.put_row(
            kernel=kernel, branch=branch, K=K, T=T, B=B, dtype="float32",
            timing=_timing(ms * 1e-3),
            device_kind=dk or kdispatch._device_kind(),
        )

    def test_three_way_pallas_win_routes_pallas(self, scratch_db):
        """THE three-way regression: with all three branches measured
        in one stamp group, the fastest (pallas) wins — the old code
        could only ever answer seq-or-assoc."""
        self._put(scratch_db, "seq", 1.0)
        self._put(scratch_db, "assoc", 0.7)
        self._put(scratch_db, "pallas", 0.3)
        obs_profile.set_db(scratch_db)
        assert kdispatch.resolve_auto(3, 999) == ("pallas", "db")
        # the legacy two-way surface degrades sanely: pallas is not assoc
        assert kdispatch.use_assoc(3, 999) is False
        # restricted arbitration (pallas-ineligible call signature):
        # the measured seq/assoc race decides, not an unmeasured default
        assert kdispatch.resolve_auto(
            3, 999, allowed=("seq", "assoc")
        ) == ("assoc", "db")

    def test_three_way_middle_branch_can_win(self, scratch_db):
        self._put(scratch_db, "seq", 1.0)
        self._put(scratch_db, "assoc", 0.3)
        self._put(scratch_db, "pallas", 0.7)
        obs_profile.set_db(scratch_db)
        assert kdispatch.resolve_auto(3, 999) == ("assoc", "db")

    def test_lone_pallas_row_does_not_route(self, scratch_db):
        """A branch that raced nothing is not a measurement of a
        crossover: a pallas-only group must leave dispatch unmeasured
        (seq default), exactly like the historical lone-assoc rule."""
        self._put(scratch_db, "pallas", 0.1)
        obs_profile.set_db(scratch_db)
        branch, source = kdispatch.resolve_auto(3, 999)
        assert branch == "seq" and source in ("table", "default")

    def test_largest_batch_group_decides_three_way(self, scratch_db):
        """B=8 says pallas, B=64 says seq: the LARGEST comparable
        batch group is the honest dispatch default and wins the
        arbitration across groups."""
        self._put(scratch_db, "seq", 1.0, B=8)
        self._put(scratch_db, "assoc", 0.7, B=8)
        self._put(scratch_db, "pallas", 0.3, B=8)
        self._put(scratch_db, "seq", 0.2, B=64)
        self._put(scratch_db, "assoc", 0.7, B=64)
        self._put(scratch_db, "pallas", 0.5, B=64)
        obs_profile.set_db(scratch_db)
        assert kdispatch.resolve_auto(3, 999) == ("seq", "db")

    def test_incomplete_larger_group_falls_to_complete_smaller(self, scratch_db):
        """A lone-branch B=64 group cannot arbitrate; the complete
        B=8 three-way group still routes."""
        self._put(scratch_db, "pallas", 0.05, B=64)
        self._put(scratch_db, "seq", 1.0, B=8)
        self._put(scratch_db, "assoc", 0.4, B=8)
        self._put(scratch_db, "pallas", 0.2, B=8)
        obs_profile.set_db(scratch_db)
        assert kdispatch.resolve_auto(3, 999) == ("pallas", "db")

    def test_exact_tie_prefers_conservative_ladder(self, scratch_db):
        self._put(scratch_db, "seq", 0.5)
        self._put(scratch_db, "assoc", 0.5)
        self._put(scratch_db, "pallas", 0.5)
        obs_profile.set_db(scratch_db)
        assert kdispatch.resolve_auto(3, 999) == ("seq", "db")

    def test_resolve_routed_degrades_only_a_pallas_winner(self, scratch_db):
        """The stamped-branch surface (wf decode cache key): the
        seq/assoc re-resolution fires ONLY when the honest arbitration
        picked pallas. Restricting up front would let a smaller/staler
        seq-assoc group decide a point whose largest-batch winner was
        seq — the stamp would then disagree with the executed branch."""
        # largest-batch group: {seq, pallas}, seq wins; smaller stale
        # group: {seq, assoc}, assoc wins
        self._put(scratch_db, "seq", 1.0, B=64)
        self._put(scratch_db, "pallas", 2.0, B=64)
        self._put(scratch_db, "seq", 1.0, B=32)
        self._put(scratch_db, "assoc", 0.5, B=32)
        obs_profile.set_db(scratch_db)
        # dispatch runs seq (B=64 group, no pallas degrade needed) —
        # the stamp must say seq too, even for a pallas-ineligible call
        assert kdispatch.resolve_routed(3, 999, pallas_ok=True) == "seq"
        assert kdispatch.resolve_routed(3, 999, pallas_ok=False) == "seq"
        # and when pallas genuinely wins, ineligible calls degrade to
        # the measured seq/assoc race (here the B=32 pair, where assoc
        # won — the B=64 group holds no complete seq/assoc race)
        self._put(scratch_db, "pallas", 0.2, B=64)
        assert kdispatch.resolve_routed(3, 999, pallas_ok=True) == "pallas"
        assert kdispatch.resolve_routed(3, 999, pallas_ok=False) == "assoc"
        with pytest.raises(ValueError, match="pallas"):
            kdispatch.resolve_routed(3, 999, "pallas", pallas_ok=False)

    def test_use_assoc_accepts_branch_names(self):
        """The two-way legacy surface under the three-way contract:
        explicit branch names pass through ('pallas' takes the
        non-assoc fork — its callers' scan arm is where the fused
        Pallas kernels live), they never raise."""
        assert kdispatch.use_assoc(3, 999, "assoc") is True
        assert kdispatch.use_assoc(3, 999, "seq") is False
        assert kdispatch.use_assoc(3, 999, "pallas") is False
        with pytest.raises(ValueError):
            kdispatch.use_assoc(3, 999, "warp")


class TestSampledFlushProfiling:
    def _scheduler(self, profile_every):
        from hhmm_tpu.models import GaussianHMM, NIGPrior
        from hhmm_tpu.serve import MicroBatchScheduler, snapshot_from_fit

        model = GaussianHMM(
            K=2, nig_prior=NIGPrior(m0=0.0, kappa0=0.1, a0=2.0, b0=1.0)
        )
        rng = np.random.default_rng(0)
        samples = rng.normal(size=(1, 16, model.n_free))
        snap = snapshot_from_fit(model, samples, n_draws=4)
        sched = MicroBatchScheduler(
            model, buckets=(4,), profile_every=profile_every
        )
        sched.attach("s0", snap)
        return sched

    def test_tracer_gated_and_compile_flat(self):
        """One scheduler drives the whole contract (the tick kernels
        compile once): with the tracer OFF the profiler never fires
        even with profile_every=1; turning the tracer ON makes it fire
        every flush WITHOUT adding a single compile (the re-timed call
        is the warm signature on the same staged inputs)."""
        trace.tracer.disable()
        try:
            sched = self._scheduler(profile_every=1)
            for t in range(3):  # init + update compiles land here
                sched.tick({"s0": {"x": 0.1 * t}})
            # production mode: knob on, tracer off -> no profiling
            assert sched.metrics.profiled_flushes == 0
            warm = sched.metrics.compile_count
            trace.tracer.enable()
            for t in range(4):
                sched.tick({"s0": {"x": 0.2 * t}})
            # every traced flush was re-timed, and NONE of it compiled
            assert sched.metrics.profiled_flushes >= 4
            assert sched.metrics.compile_count == warm
            snap = obs_metrics.snapshot()
            keys = [k for k in snap if k.startswith("serve.flush_device_time_ms")]
            assert keys, snap.keys()
            assert snap[keys[0]]["value"] > 0
        finally:
            trace.tracer.use_env()
            trace.reset()
            obs_metrics.use_env()

    def test_default_off_and_validation(self):
        from hhmm_tpu.models import GaussianHMM, NIGPrior
        from hhmm_tpu.serve import MicroBatchScheduler

        model = GaussianHMM(
            K=2, nig_prior=NIGPrior(m0=0.0, kappa0=0.1, a0=2.0, b0=1.0)
        )
        # off by default: no flush ever profiles (checked structurally —
        # _maybe_profile_flush's first guard — without paying a compile)
        assert MicroBatchScheduler(model, buckets=(4,)).profile_every == 0
        with pytest.raises(ValueError):
            MicroBatchScheduler(model, buckets=(4,), profile_every=-1)


class TestCheckGuardsInvariant9:
    def _run_on(self, root):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "check_guards.py"),
             str(root)],
            capture_output=True,
            text=True,
        )

    def test_repo_passes(self, check_guards_repo):
        proc = check_guards_repo  # one shared repo scan (conftest)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "timing loops confined" in proc.stdout

    def test_raw_timing_loop_flagged(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "bad.py").write_text(
            "from time import perf_counter\n"
            "import jax\n\n"
            "def timed(fn, sets, reps):\n"
            "    t0 = perf_counter()\n"
            "    for r in range(reps):\n"
            "        jax.block_until_ready(fn(*sets[r]))\n"
            "    return perf_counter() - t0\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "timing loop" in proc.stdout and "device_time" in proc.stdout

    def test_attribute_spelling_flagged(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "bad2.py").write_text(
            "import time as _t\n"
            "import jax\n\n"
            "def timed(fn, x, reps):\n"
            "    t0 = _t.perf_counter()\n"
            "    r = 0\n"
            "    while r < reps:\n"
            "        jax.block_until_ready(fn(x))\n"
            "        r += 1\n"
            "    return _t.perf_counter() - t0\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "timing loop" in proc.stdout

    def test_per_iteration_attribution_allowed(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "ok.py").write_text(
            "from time import perf_counter\n"
            "import jax\n\n"
            "def phases(fn, sets):\n"
            "    acc = 0.0\n"
            "    t0 = perf_counter()\n"
            "    for s in sets:\n"
            "        jax.block_until_ready(fn(*s))\n"
            "        acc += perf_counter() - t0\n"
            "        t0 = perf_counter()\n"
            "    return acc + perf_counter() - t0\n"
        )
        proc = self._run_on(tmp_path)
        assert "timing loop" not in proc.stdout

    def test_nested_def_is_its_own_scope(self, tmp_path):
        """(a) a violating loop inside a nested def is reported ONCE,
        not re-reported through the enclosing function; (b) an
        enclosing function's unrelated clock reads never bracket a
        nested helper's clock-free sync loop into a false positive."""
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "nested_bad.py").write_text(
            "from time import perf_counter\n"
            "import jax\n\n"
            "def outer(fn, sets):\n"
            "    t0 = perf_counter()\n\n"
            "    def timed(reps):\n"
            "        t1 = perf_counter()\n"
            "        for r in range(reps):\n"
            "            jax.block_until_ready(fn(*sets[r]))\n"
            "        return perf_counter() - t1\n\n"
            "    return timed(3), perf_counter() - t0\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.stdout.count("timing loop") == 1, proc.stdout
        (pkg / "nested_bad.py").unlink()
        (pkg / "nested_ok.py").write_text(
            "from time import perf_counter\n"
            "import jax\n\n"
            "def outer(fn, sets):\n"
            "    t0 = perf_counter()\n\n"
            "    def sync_all():\n"
            "        for s in sets:\n"
            "            jax.block_until_ready(fn(*s))\n\n"
            "    sync_all()\n"
            "    return perf_counter() - t0\n"
        )
        proc = self._run_on(tmp_path)
        assert "timing loop" not in proc.stdout, proc.stdout

    def test_harness_module_exempt(self, tmp_path):
        obs = tmp_path / "hhmm_tpu" / "obs"
        obs.mkdir(parents=True)
        (obs / "profile.py").write_text(
            "from time import perf_counter\n"
            "import jax\n\n"
            "def device_time(fn, sets, reps):\n"
            "    t0 = perf_counter()\n"
            "    for r in range(reps):\n"
            "        jax.block_until_ready(fn(*sets[r]))\n"
            "    return perf_counter() - t0\n"
        )
        proc = self._run_on(tmp_path)
        assert "timing loop" not in proc.stdout


class TestBenchDiffKernelCosts:
    def _record(self, n, p50, extra_row=None):
        rows = [
            {"kernel": "filter", "branch": "seq", "K": 4, "T": 64, "B": 4,
             "dtype": "float32", "p50_ms": p50},
        ]
        if extra_row is not None:
            rows.append(extra_row)
        return {
            "n": n, "rc": 0,
            "parsed": {
                "metric": "hmm_kernel_profile_throughput",
                "value": 100.0, "unit": "series/sec", "backend": "cpu",
                "manifest": {
                    "workload_digest": "w", "backend": "cpu",
                    "device_kind": "cpu", "versions": {"jax": "0.4.37"},
                    "trace_enabled": False,
                    "kernel_costs": {"rows": rows},
                },
            },
        }

    def _run(self, d):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "bench_diff.py"),
             "--dir", str(d)],
            capture_output=True,
            text=True,
        )

    def _write(self, d, *recs):
        for r in recs:
            with open(os.path.join(str(d), f"BENCH_r{r['n']:02d}.json"), "w") as f:
                json.dump(r, f)

    def test_device_time_regression_fails(self, tmp_path):
        self._write(tmp_path, self._record(1, 1.0), self._record(2, 1.5))
        proc = self._run(tmp_path)
        assert proc.returncode == 1
        assert "DEVICE-TIME REGRESSION" in proc.stdout

    def test_improvement_and_within_threshold_pass(self, tmp_path):
        self._write(tmp_path, self._record(1, 1.0), self._record(2, 0.7))
        assert self._run(tmp_path).returncode == 0
        self._write(tmp_path, self._record(1, 1.0), self._record(2, 1.05))
        proc = self._run(tmp_path)
        assert proc.returncode == 0
        assert "kernel costs ok" in proc.stdout

    def test_unmeasured_rows_reported_ungated(self, tmp_path):
        unmeasured = {"kernel": "ffbs", "branch": "assoc", "K": 4, "T": 64,
                      "B": 4, "dtype": "float32", "p50_ms": None}
        self._write(
            tmp_path,
            self._record(1, 1.0, extra_row=unmeasured),
            self._record(2, 1.0, extra_row=unmeasured),
        )
        proc = self._run(tmp_path)
        assert proc.returncode == 0
        assert "unmeasured kernel row(s) ungated" in proc.stdout

    def test_first_record_is_baseline(self, tmp_path):
        self._write(tmp_path, self._record(1, 1.0))
        proc = self._run(tmp_path)
        assert proc.returncode == 0
        assert "kernel-cost baseline" in proc.stdout

    def test_pallas_rows_gate_under_same_key(self, tmp_path):
        """branch="pallas" rows ride the existing per-row
        (kernel/branch/K/T/B/dtype) comparability key: a pallas
        device-time regression fails the gate like any other branch,
        and seq rows at the same (K, T, B) stay independent."""
        pallas = lambda p50: {"kernel": "filter", "branch": "pallas", "K": 4,
                              "T": 64, "B": 4, "dtype": "float32", "p50_ms": p50}
        self._write(
            tmp_path,
            self._record(1, 1.0, extra_row=pallas(0.4)),
            self._record(2, 1.0, extra_row=pallas(0.7)),
        )
        proc = self._run(tmp_path)
        assert proc.returncode == 1
        assert "DEVICE-TIME REGRESSION" in proc.stdout
        assert "pallas" in proc.stdout
        # improvement on the pallas row alone passes
        self._write(
            tmp_path,
            self._record(1, 1.0, extra_row=pallas(0.4)),
            self._record(2, 1.0, extra_row=pallas(0.3)),
        )
        assert self._run(tmp_path).returncode == 0


class TestObsReportCostPlane:
    MANIFEST = os.path.join(FIXTURES, "obs_report_manifest.json")

    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
             *argv],
            capture_output=True,
            text=True,
        )

    def test_cost_section_from_fixture(self):
        """The acceptance criterion: the cost section renders from the
        checked-in fixture (and obs_report still imports no jax —
        asserted by tests/test_obs.py)."""
        proc = self._run(self.MANIFEST)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        out = proc.stdout
        assert "== kernel costs ==" in out
        assert "filter[seq]" in out and "filter[assoc]" in out
        assert "filter[pallas]" in out and "viterbi[pallas]" in out
        assert "timing-only" in out
        assert "DB-backed" in out
        assert "unmeasured (scan default)" in out
        # the three-way dispatch audit: the raced branch enum renders
        # per audit line, and a measured pallas winner shows as such
        assert "raced branches: seq/assoc/pallas" in out
        assert "[raced seq/assoc/pallas]" in out
        assert "pallas (DB-backed)" in out

    def test_storm_and_resilience_from_fixture(self):
        proc = self._run(self.MANIFEST)
        out = proc.stdout
        assert "== storm ==" in out
        assert "faults escaped: 0" in out
        assert "verdict: SURVIVED" in out
        assert "shed ticks: 1843" in out
        assert "pager evictions: 941" in out
        assert "device loss events: 2" in out

    def test_no_cost_rows_renders_placeholder(self, tmp_path):
        man = {"version": 1, "metrics": {}}
        p = tmp_path / "man.json"
        p.write_text(json.dumps(man))
        proc = self._run(str(p))
        assert proc.returncode == 0
        assert "(no kernel-cost rows in this run)" in proc.stdout
        assert "== storm ==" not in proc.stdout  # storms are rare: no stanza, no section


class TestProfileKernelsBench:
    def test_quick_steered_to_scratch_db(self):
        """`--quick` without an explicit out path must never write into
        the checked-in results/kernel_costs.json — reps=2/B=4 smoke
        rows would otherwise (if committed) decide dispatch off
        2-rep noise."""
        import argparse
        import bench

        quick = argparse.Namespace(kernel_costs_out=None, quick=True)
        assert bench.kernel_costs_path(quick).endswith("kernel_costs.quick.json")
        full = argparse.Namespace(kernel_costs_out=None, quick=False)
        assert bench.kernel_costs_path(full) is None  # profile.py default
        explicit = argparse.Namespace(kernel_costs_out="/tmp/x.json", quick=True)
        assert bench.kernel_costs_path(explicit) == "/tmp/x.json"

    @pytest.mark.slow  # ~20 s subprocess: the fast DB/dispatch contract
    # tests above stay tier-1; this is the end-to-end artifact check
    def test_quick_cpu_populates_db_and_dispatch_reads_it(self, tmp_path):
        """The end-to-end acceptance run: ``bench.py --profile-kernels
        --quick`` on CPU emits a kernel_costs.json covering the scan vs
        assoc filter/FFBS branches at 3 (K, T) points, and the stanza's
        dispatch audit shows "auto" resolving from the DB."""
        db_path = str(tmp_path / "kernel_costs.json")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--profile-kernels", "--quick", "--cpu",
             "--kernel-costs-out", db_path],
            capture_output=True,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        with open(db_path) as f:
            db = json.load(f)
        assert db["version"] == 1
        rows = list(db["rows"].values())
        covered = {(r["kernel"], r["branch"]) for r in rows}
        # --quick races the FULL branch enum (pallas through the
        # interpreter, steered to the scratch DB): three-way rows at
        # the same (K, T, B) points
        assert {("filter", "seq"), ("filter", "assoc"), ("filter", "pallas"),
                ("ffbs", "seq"), ("ffbs", "assoc"), ("ffbs", "pallas")} <= covered
        assert len({(r["K"], r["T"]) for r in rows}) >= 3
        for r in rows:  # every row stamped + measured
            assert r["device_kind"] == "cpu"
            assert r["jax"]
            assert r["timing"]["p50_s"] > 0
        record = json.loads(proc.stdout.strip().splitlines()[-1])
        assert record["metric"] == "hmm_kernel_profile_throughput"
        kc = record["manifest"]["kernel_costs"]
        assert len(kc["rows"]) == len(rows)
        assert kc["branches"] == ["seq", "assoc", "pallas"]
        assert kc["dispatch"], kc
        assert all(d["source"] == "db" for d in kc["dispatch"])
        # the three-way audit: every point records the raced enum
        assert all(d["raced"] == ["seq", "assoc", "pallas"] for d in kc["dispatch"])
        # CPU truth (PR 3): the sequential scan wins the batched
        # FILTER points decisively (4-10x) — now DB-backed instead of
        # empty-table-defaulted. (ffbs is near-parity at these tiny
        # quick shapes, so its winner is honest measurement noise —
        # asserted only as DB-backed above.)
        assert all(
            d["auto"] == "seq" for d in kc["dispatch"] if d["kernel"] == "filter"
        )
