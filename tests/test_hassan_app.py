"""Hassan application tests: dataset construction, the
likelihood-neighbor forecaster (hand oracle + reference weight quirk),
error metrics, and the batched walk-forward harness on synthetic OHLC."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute suites; fast subset: -m 'not slow'

from hhmm_tpu.apps.hassan import (
    forecast_errors,
    make_dataset,
    neighbouring_forecast,
    simulate_ohlc,
    wf_forecast,
)


class TestDataset:
    def test_structure_and_scaling(self):
        rng = np.random.default_rng(0)
        ohlc = simulate_ohlc(rng, T=100)
        ds = make_dataset(ohlc, scale=True)
        assert ds.x.shape == (99,)
        assert ds.u.shape == (99, 4)
        # x_t is close[t+1], u_t is day-t OHLC (`data.R:29-30`)
        np.testing.assert_allclose(ds.x_unscaled, ohlc[1:, 3])
        np.testing.assert_allclose(ds.u_unscaled, ohlc[:-1])
        # scaling round-trips
        np.testing.assert_allclose(ds.unscale_x(ds.x), ds.x_unscaled)
        assert abs(ds.x.mean()) < 1e-10 and abs(ds.x.std(ddof=1) - 1) < 1e-10

    def test_unscaled(self):
        rng = np.random.default_rng(1)
        ohlc = simulate_ohlc(rng, T=50)
        ds = make_dataset(ohlc, scale=False)
        np.testing.assert_array_equal(ds.x, ds.x_unscaled)
        assert ds.x_scale == 1.0


class TestForecaster:
    def test_hand_oracle(self):
        """3 candidates, one within the relative band: the forecast is
        x[-1] + that neighbor's h-ahead change."""
        x = np.array([1.0, 2.0, 5.0, 3.0, 4.0])
        # target oblik −1.0; candidates (first 4): only index 1 within 5%
        oblik = np.array([[-2.0, -0.99, -3.0, -2.5, -1.0]])
        f = neighbouring_forecast(x, oblik, h=1, threshold=0.05)
        np.testing.assert_allclose(f, [4.0 + (5.0 - 2.0)])

    def test_fallback_to_closest(self):
        x = np.array([1.0, 2.0, 3.0, 4.0])
        oblik = np.array([[-9.0, -5.0, -8.0, -1.0]])  # none within band
        f = neighbouring_forecast(x, oblik, h=1, threshold=0.05)
        # closest is index 1 (|−1−(−5)|=4 < others) → x[-1] + (x[2]−x[1])
        np.testing.assert_allclose(f, [4.0 + 1.0])

    def test_reference_weight_quirk(self):
        """Two qualifying neighbors: the reference upweights the FARTHER
        one (w = exp(+d)); 'inverse' prefers the nearer."""
        x = np.array([0.0, 10.0, 0.0, -10.0, 0.0])
        oblik = np.array([[-100.0, -100.0, -100.04, -104.0, -100.01]])
        # candidates idx 0..3; within 5% band of −100.01: all of them
        ref = neighbouring_forecast(x, oblik, h=1, threshold=0.05)
        inv = neighbouring_forecast(x, oblik, h=1, threshold=0.05, weights="inverse")
        assert ref[0] != inv[0]

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError, match="length"):
            neighbouring_forecast(np.arange(3.0), np.zeros((2, 4)))

    def test_errors(self):
        actual = np.array([10.0, 20.0, 30.0])
        pred = np.array([11.0, 19.0, 33.0])
        e = forecast_errors(actual, pred)
        np.testing.assert_allclose(e["mse"], (1 + 1 + 9) / 3)
        np.testing.assert_allclose(
            e["mape"], 100 * np.mean([1 / 10, 1 / 20, 3 / 30])
        )
        assert e["r2"] < 1.0


class TestWalkForward:
    def test_wf_forecast_end_to_end(self, tmp_path):
        """Synthetic persistent-drift OHLC: the batched walk-forward
        forecaster must beat the naive random-walk R² materially (the
        reference reports R² ≈ 0.87-0.94 on real closes)."""
        from hhmm_tpu.infer import SamplerConfig

        rng = np.random.default_rng(5)
        # a trending series: over the 10 OOS days the level moves far
        # more than the per-day noise, so a forecaster that tracks the
        # level must get high R² vs the constant-mean baseline (matching
        # the regime of the reference's real-close experiments, where
        # R² ≈ 0.87-0.94 comes from trending price levels)
        ohlc = simulate_ohlc(
            rng, T=120, vol=0.008, regimes=1, drift_spread=-0.02
        )
        res = wf_forecast(
            ohlc,
            train_len=110,
            K=2,
            L=2,
            config=SamplerConfig(
                num_warmup=150, num_samples=150, num_chains=1, max_treedepth=6
            ),
            cache_dir=str(tmp_path),
            chunk_size=16,
        )
        assert res.forecasts.shape[0] == 10
        assert res.point.shape == (10,)
        assert np.isfinite(res.point).all()
        assert res.diverged.mean() < 0.2
        # forecasts stay in a sane band around the realized closes
        assert res.errors["mape"] < 10.0
        # every forecast must be strictly out of sample: the anchor
        # close (last training obs) differs from the realized target
        anchors = ohlc[109:119, 3]
        assert not np.allclose(res.actual, anchors)
        # the level moves ~20% over the OOS span: tracking it beats the
        # constant-mean baseline decisively
        assert res.errors["r2"] > 0.5

    def test_warm_start_matches_cold_start(self):
        """Warm-started windows must converge to the SAME posterior as
        cold starts — the evidence behind the idiomatic improvement over
        the reference's from-scratch refits (`hassan2005/main.Rmd:795`).
        Identical data and sampler budgets, only the chain inits differ;
        per-step posterior-mean forecasts and log-densities must agree
        within MC error."""
        from hhmm_tpu.infer import SamplerConfig
        import jax

        rng = np.random.default_rng(11)
        ohlc = simulate_ohlc(rng, T=100, vol=0.01, regimes=1)
        cfg = SamplerConfig(
            num_warmup=250, num_samples=250, num_chains=2, max_treedepth=6
        )
        kwargs = dict(
            ohlc=ohlc, train_len=94, K=2, L=2, config=cfg, chunk_size=8,
            key=jax.random.PRNGKey(42),
        )
        warm = wf_forecast(warm_start=True, **kwargs)
        cold = wf_forecast(warm_start=False, **kwargs)
        assert warm.diverged.mean() < 0.2 and cold.diverged.mean() < 0.2
        # posterior-mean point forecasts: same posterior => agreement
        # within the Monte-Carlo spread of the forecast distributions
        mc_se = np.maximum(
            warm.forecasts.std(axis=1) / np.sqrt(20),
            cold.forecasts.std(axis=1) / np.sqrt(20),
        )
        gap = np.abs(warm.point - cold.point)
        assert (gap <= 5.0 * mc_se + 1e-3).all(), (gap, mc_se)
