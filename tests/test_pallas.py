"""Fused Pallas forward+backward value-and-grad on the unified blocked
semiring kernel (kernels/pallas_semiring.py::semiring_vg — the
contract the retired kernels/pallas_forward[_chunked].py shims keep)
and the custom_vmap dispatcher (kernels/vg.py), in interpreter mode on
CPU. The real-TPU path is exercised by bench.py on hardware. Imports
go through `kernels/dispatch.py`, the only sanctioned Pallas entry
outside the kernels package (analysis rule ``pallas-import``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hhmm_tpu.core.lmath import MASK_NEG, log_normalize, safe_log
from hhmm_tpu.kernels.dispatch import semiring_vg
from hhmm_tpu.kernels.vg import _vg_single, forward_value_and_grad


def pallas_forward_vg(
    log_pi, log_A, log_obs, mask, gate_key=None, state_key=None, *, interpret=False
):
    """The retired resident kernel's call shape on the unified blocked
    kernel: one block owns the whole sequence (``t_block=T``), so the
    whole recursion stays VMEM-resident — exactly what
    `kernels/pallas_forward.py::pallas_forward_vg` shims to."""
    return semiring_vg(
        log_pi, log_A, log_obs, mask, gate_key, state_key,
        t_block=log_obs.shape[1], interpret=interpret,
    )


def _batch(rng, B, T, K, ragged=False):
    log_pi = log_normalize(jnp.asarray(rng.normal(size=(B, K))))
    log_A = log_normalize(jnp.asarray(rng.normal(size=(B, K, K))), axis=-1)
    log_obs = jnp.asarray(rng.normal(size=(B, T, K)) - 1.0)
    if ragged:
        lengths = rng.integers(T // 2, T + 1, size=B)
        mask = jnp.asarray((np.arange(T)[None] < lengths[:, None]).astype(np.float32))
    else:
        mask = jnp.ones((B, T), jnp.float32)
    return log_pi.astype(jnp.float32), log_A.astype(jnp.float32), log_obs.astype(
        jnp.float32
    ), mask


def _ref(log_pi, log_A, log_obs, mask):
    return jax.vmap(_vg_single)(log_pi, log_A, log_obs, mask)


def _assert_close(out, ref, rtol=3e-4, atol=3e-5):
    for a, b, name in zip(out, ref, ("ll", "d_pi", "d_A", "d_obs")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=rtol, atol=atol, err_msg=name
        )


class TestPallasKernel:
    # B=128 (exact tile multiple) measured multi-second on the
    # single-core tier-1 host (.tier1_durations.json) — slow-marked;
    # B=130 keeps the large-batch path in tier-1 and is the stricter
    # case (full tiles + ragged remainder)
    @pytest.mark.parametrize(
        "B",
        [1, 5, pytest.param(128, marks=pytest.mark.slow), 130],
    )
    def test_matches_reference(self, rng, B):
        args = _batch(rng, B, 33, 4)
        out = pallas_forward_vg(*args, interpret=True)
        _assert_close(out, _ref(*args))

    def test_ragged_masks(self, rng):
        args = _batch(rng, 9, 40, 4, ragged=True)
        out = pallas_forward_vg(*args, interpret=True)
        _assert_close(out, _ref(*args))
        # padding steps have zero obs-gradient
        dobs = np.asarray(out[3])
        m = np.asarray(args[3])
        assert np.all(dobs[m == 0.0] == 0.0)

    def test_gated_tayal_shapes(self, rng):
        """Sparse MASK_NEG-gated transitions (hard-gated Tayal sparse A)."""
        B, T, K = 4, 50, 4
        log_pi, log_A, log_obs, mask = _batch(rng, B, T, K)
        gate = jnp.asarray(rng.random((B, K, K)) < 0.4)
        log_A = jnp.where(gate, MASK_NEG, log_A)
        pi_gate = jnp.asarray(rng.random((B, K)) < 0.3)
        log_pi = jnp.where(pi_gate, safe_log(jnp.zeros(())), log_pi)
        out = pallas_forward_vg(log_pi, log_A, log_obs, mask, interpret=True)
        ref = _ref(log_pi, log_A, log_obs, mask)
        for o in out:
            assert np.all(np.isfinite(np.asarray(o)))
        _assert_close(out, ref)

    def test_K3(self, rng):
        args = _batch(rng, 3, 21, 3)
        out = pallas_forward_vg(*args, interpret=True)
        _assert_close(out, _ref(*args))


class TestDispatcher:
    def test_single_equals_reference(self, rng):
        lp, lA, lo, m = _batch(rng, 1, 19, 3)
        out = forward_value_and_grad(lp[0], lA[0], lo[0], m[0])
        ref = _vg_single(lp[0], lA[0], lo[0], m[0])
        _assert_close(out, ref)

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); single-level vmap is subsumed by test_vmap_nested_folds, which stays tier-1
    def test_vmap_once(self, rng):
        args = _batch(rng, 6, 17, 4)
        out = jax.vmap(forward_value_and_grad)(*args)
        _assert_close(out, _ref(*args))

    def test_vmap_nested_folds(self, rng):
        """series x chains nesting — the bench/sampler structure."""
        S, C, T, K = 3, 2, 15, 4
        lp, lA, lo, m = _batch(rng, S * C, T, K)
        lp2, lA2, lo2 = (
            x.reshape((S, C) + x.shape[1:]) for x in (lp, lA, lo)
        )
        m2 = m.reshape(S, C, T)
        out = jax.vmap(jax.vmap(forward_value_and_grad))(lp2, lA2, lo2, m2)
        ref = _ref(lp, lA, lo, m)
        ref2 = tuple(r.reshape((S, C) + r.shape[1:]) for r in ref)
        _assert_close(out, ref2)

    def test_vmap_unbatched_args_broadcast(self, rng):
        """mask shared across chains (the in-sampler case)."""
        lp, lA, lo, m = _batch(rng, 4, 12, 3)
        out = jax.vmap(forward_value_and_grad, in_axes=(0, 0, 0, None))(
            lp, lA, lo, m[0]
        )
        ref = _ref(lp, lA, lo, jnp.broadcast_to(m[0], m.shape))
        _assert_close(out, ref)

    def test_time_varying_falls_back(self, rng):
        B, T, K = 3, 11, 3
        lp = log_normalize(jnp.asarray(rng.normal(size=(B, K)))).astype(jnp.float32)
        lA = log_normalize(
            jnp.asarray(rng.normal(size=(B, T - 1, K, K))), axis=-1
        ).astype(jnp.float32)
        lo = jnp.asarray(rng.normal(size=(B, T, K))).astype(jnp.float32)
        m = jnp.ones((B, T), jnp.float32)
        out = jax.vmap(forward_value_and_grad)(lp, lA, lo, m)
        ref = _ref(lp, lA, lo, m)
        _assert_close(out, ref)

    def test_jit_compatible(self, rng):
        args = _batch(rng, 5, 13, 4)
        out = jax.jit(jax.vmap(forward_value_and_grad))(*args)
        _assert_close(out, _ref(*args))


class TestSamplerVgPath:
    @pytest.mark.slow
    def test_vg_matches_logp_path(self, rng):
        """sample_nuts(vg_fn=...) reproduces the logp path exactly on CPU
        (identical numerics -> identical chains)."""
        from hhmm_tpu.infer import SamplerConfig, sample_nuts
        from hhmm_tpu.models import TayalHHMM

        model = TayalHHMM()
        T = 60
        x = jnp.asarray(rng.integers(0, 9, size=T))
        sign = jnp.asarray(np.arange(T) % 2)
        data = {"x": x, "sign": sign}
        theta0 = model.init_unconstrained(jax.random.PRNGKey(0), data)
        cfg = SamplerConfig(num_warmup=30, num_samples=30, num_chains=2, max_treedepth=6)
        key = jax.random.PRNGKey(1)

        qs_a, st_a = sample_nuts(model.make_logp(data), key, theta0, cfg)
        qs_b, st_b = sample_nuts(None, key, theta0, cfg, vg_fn=model.make_vg(data))
        np.testing.assert_allclose(
            np.asarray(qs_a), np.asarray(qs_b), rtol=1e-4, atol=1e-5
        )

    @pytest.mark.slow
    def test_vg_vmapped_over_series(self, rng):
        """The bench structure: vmap over series around sample_nuts."""
        from hhmm_tpu.infer import SamplerConfig, sample_nuts
        from hhmm_tpu.models import TayalHHMM

        model = TayalHHMM()
        B, T = 3, 40
        x = jnp.asarray(rng.integers(0, 9, size=(B, T)))
        sign = jnp.asarray(np.broadcast_to(np.arange(T) % 2, (B, T)))
        init = jnp.stack(
            [
                model.init_unconstrained(jax.random.PRNGKey(i), {"x": x[i], "sign": sign[i]})
                for i in range(B)
            ]
        )[:, None, :]
        keys = jax.random.split(jax.random.PRNGKey(5), B)
        cfg = SamplerConfig(num_warmup=20, num_samples=10, num_chains=1, max_treedepth=5)

        def one(xi, si, qi, ki):
            vg = model.make_vg({"x": xi, "sign": si})
            qs, stats = sample_nuts(None, ki, qi, cfg, jit=False, vg_fn=vg)
            return qs, stats["logp"]

        qs, logps = jax.jit(jax.vmap(one))(x, sign, init, keys)
        assert qs.shape == (B, 1, cfg.num_samples, model.n_free)
        assert np.all(np.isfinite(np.asarray(logps)))


class TestGatedPath:
    def _gated_args(self, rng, B, T, K):
        lp, lA, lo, m = _batch(rng, B, T, K)
        gate_key = jnp.asarray((rng.integers(0, 2, size=(B, T))).astype(np.float32))
        state_key = jnp.asarray((rng.integers(0, 2, size=(B, K))).astype(np.float32))
        return lp, lA, lo, m, gate_key, state_key

    def test_kernel_matches_reference(self, rng):
        from hhmm_tpu.kernels.vg import _vg_single_gated

        args = self._gated_args(rng, 7, 29, 4)
        out = pallas_forward_vg(args[0], args[1], args[2], args[3],
                                gate_key=args[4], state_key=args[5], interpret=True)
        ref = jax.vmap(_vg_single_gated)(*args)
        _assert_close(out, ref)

    def test_kernel_gated_ragged_masks(self, rng):
        """Gate x ragged-mask interaction in the fused kernel: padded
        steps must carry alpha/beta through and contribute no gradient
        even while gating is active."""
        from hhmm_tpu.kernels.vg import _vg_single_gated

        lp, lA, lo, m = _batch(rng, 9, 40, 4, ragged=True)
        gate_key = jnp.asarray((rng.integers(0, 2, size=(9, 40))).astype(np.float32))
        state_key = jnp.asarray((rng.integers(0, 2, size=(9, 4))).astype(np.float32))
        out = pallas_forward_vg(lp, lA, lo, m, gate_key=gate_key,
                                state_key=state_key, interpret=True)
        ref = jax.vmap(_vg_single_gated)(lp, lA, lo, m, gate_key, state_key)
        _assert_close(out, ref)
        dobs = np.asarray(out[3])
        assert np.all(dobs[np.asarray(m) == 0.0] == 0.0)

    def test_gated_op_vmap(self, rng):
        from hhmm_tpu.kernels.vg import _vg_single_gated

        args = self._gated_args(rng, 5, 18, 4)
        out = jax.vmap(forward_value_and_grad)(*args)
        ref = jax.vmap(_vg_single_gated)(*args)
        _assert_close(out, ref)

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_tayal_stan_vg_matches_autodiff(self, rng):
        """make_vg (gated op + onehot emissions) == grad(make_logp)
        (time-varying gated A + custom VJP) for the stan-parity mode."""
        from hhmm_tpu.models import TayalHHMM

        model = TayalHHMM(gate_mode="stan")
        T = 70
        x = jnp.asarray(rng.integers(0, 9, size=T))
        sign = jnp.asarray(np.arange(T) % 2)
        data = {"x": x, "sign": sign}
        logp = model.make_logp(data)
        vg = model.make_vg(data)
        for seed in range(3):
            theta = model.init_unconstrained(jax.random.PRNGKey(seed), data)
            v, g = vg(theta)
            v_ref, g_ref = jax.value_and_grad(logp)(theta)
            np.testing.assert_allclose(float(v), float(v_ref), rtol=1e-5)
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(g_ref), rtol=3e-4, atol=1e-5
            )

    def test_semisup_stan_vg_matches_autodiff(self, rng):
        from hhmm_tpu.models import SemisupMultinomialHMM

        model = SemisupMultinomialHMM(K=4, L=5, groups=(0, 1, 1, 0), gate_mode="stan")
        T = 50
        z_groups = rng.integers(0, 2, size=T)
        data = {
            "x": jnp.asarray(rng.integers(0, 5, size=T)),
            "g": jnp.asarray(z_groups),
        }
        logp = model.make_logp(data)
        vg = model.make_vg(data)
        theta = model.init_unconstrained(jax.random.PRNGKey(0), data)
        v, g = vg(theta)
        v_ref, g_ref = jax.value_and_grad(logp)(theta)
        np.testing.assert_allclose(float(v), float(v_ref), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=3e-4, atol=1e-5)

    def test_hard_mode_vg_matches_autodiff(self, rng):
        from hhmm_tpu.models import TayalHHMM

        model = TayalHHMM(gate_mode="hard")
        T = 40
        x = jnp.asarray(rng.integers(0, 9, size=T))
        sign = jnp.asarray(np.arange(T) % 2)
        data = {"x": x, "sign": sign}
        theta = model.init_unconstrained(jax.random.PRNGKey(0), data)
        v, g = model.make_vg(data)(theta)
        v_ref, g_ref = jax.value_and_grad(model.make_logp(data))(theta)
        np.testing.assert_allclose(float(v), float(v_ref), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=3e-4, atol=1e-5)


class TestIOHMMFold:
    """The rank-1 IOHMM transition collapses into effective emissions
    (models/iohmm.py build_vg), making the family homogeneous-A and
    Pallas-eligible. Exact in f64; f32 tolerances cover reassociation."""

    # both dense combos and ragged-stan measured multi-second on the
    # single-core tier-1 host (.tier1_durations.json: ragged-stan
    # 10.5 s vs ragged-gen 1.6 s) — slow-marked; ragged-gen keeps the
    # fold-vs-autodiff contract (the stricter masked case) in tier-1,
    # and the stan-mode vg contract stays tier-1 through
    # TestGatedPath::test_semisup_stan_vg_matches_autodiff
    @pytest.mark.parametrize(
        "ragged, mode",
        [
            pytest.param(
                False, "stan", id="dense-stan", marks=pytest.mark.slow
            ),
            pytest.param(
                False, "gen", id="dense-gen", marks=pytest.mark.slow
            ),
            pytest.param(
                True, "stan", id="ragged-stan", marks=pytest.mark.slow
            ),
            pytest.param(True, "gen", id="ragged-gen"),
        ],
    )
    def test_vg_matches_autodiff(self, rng, mode, ragged):
        from hhmm_tpu.apps.hassan.wf import DEFAULT_HYPERPARAMS
        from hhmm_tpu.models import IOHMMHMix, IOHMMReg
        from hhmm_tpu.sim import iohmm_sim, obsmodel_reg

        K, M, T = 3, 4, 120
        u = np.column_stack([np.ones(T), rng.normal(size=(T, M - 1))])
        sim = iohmm_sim(
            jax.random.PRNGKey(0), u, rng.normal(size=(K, M)),
            obsmodel_reg(rng.normal(size=(K, M)), np.full(K, 0.4)),
        )
        for model in (
            IOHMMReg(K=K, M=M, trans_mode=mode),
            IOHMMHMix(K=K, M=M, L=3, hyperparams=DEFAULT_HYPERPARAMS, trans_mode=mode),
        ):
            data = {"u": jnp.asarray(sim["u"]), "x": jnp.asarray(sim["x"])}
            if ragged:
                data["mask"] = jnp.asarray((np.arange(T) < 87).astype(np.float32))
            theta = jnp.asarray(model.init_unconstrained(jax.random.PRNGKey(1), data))
            v_ref, g_ref = jax.value_and_grad(model.make_logp(data))(theta)
            v_vg, g_vg = model.make_vg(data)(theta)
            np.testing.assert_allclose(float(v_ref), float(v_vg), rtol=2e-5)
            np.testing.assert_allclose(
                np.asarray(g_ref), np.asarray(g_vg), rtol=2e-3, atol=1e-3
            )

    def test_single_step_series(self, rng):
        """T=1: no transitions to fold."""
        from hhmm_tpu.models import IOHMMReg

        model = IOHMMReg(K=2, M=2)
        data = {
            "u": jnp.asarray(rng.normal(size=(1, 2))),
            "x": jnp.asarray(rng.normal(size=(1,))),
        }
        theta = jnp.asarray(model.init_unconstrained(jax.random.PRNGKey(0), data))
        v_ref, g_ref = jax.value_and_grad(model.make_logp(data))(theta)
        v_vg, g_vg = model.make_vg(data)(theta)
        np.testing.assert_allclose(float(v_ref), float(v_vg), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g_ref), np.asarray(g_vg), rtol=1e-3, atol=1e-4)


class TestChunkedKernel:
    """Chunked-T streaming variant (kernels/pallas_forward_chunked.py):
    exact parity with the lax.scan reference across chunk boundaries,
    ragged masks, non-multiple T, and sign gating — the long-window
    path the walk-forward fit uses."""

    def _run(self, args, gate=None, t_chunk=16):
        # the retired chunked kernel's schedule: t_block < T streams
        # the sequence through VMEM blocks with a cross-block carry
        if gate is None:
            return semiring_vg(*args, t_block=t_chunk, interpret=True)
        return semiring_vg(*args, *gate, t_block=t_chunk, interpret=True)

    @pytest.mark.parametrize("T", [16, 33, 48, 100])
    def test_matches_reference_across_chunk_boundaries(self, rng, T):
        args = _batch(rng, 5, T, 4)
        out = self._run(args, t_chunk=16)
        _assert_close(out, _ref(*args))

    def test_single_chunk_degenerate(self, rng):
        args = _batch(rng, 3, 12, 4)
        out = self._run(args, t_chunk=16)
        _assert_close(out, _ref(*args))

    def test_ragged_masks(self, rng):
        args = _batch(rng, 9, 70, 4, ragged=True)
        out = self._run(args, t_chunk=16)
        _assert_close(out, _ref(*args))
        dobs = np.asarray(out[3])
        m = np.asarray(args[3])
        assert np.all(dobs[m == 0.0] == 0.0)

    def test_gated_matches_reference(self, rng):
        """Soft sign-gating via [T] keys (the Tayal stan-gate hot
        loop) across chunk boundaries."""
        from hhmm_tpu.kernels.vg import _vg_single_gated

        B, T, K = 6, 53, 4
        log_pi, log_A, log_obs, mask = _batch(rng, B, T, K)
        gate_key = jnp.asarray(rng.integers(0, 2, (B, T)), jnp.float32)
        state_key = jnp.asarray(rng.integers(0, 2, (B, K)), jnp.float32)
        out = self._run(
            (log_pi, log_A, log_obs, mask), gate=(gate_key, state_key),
            t_chunk=16,
        )
        ref = jax.vmap(_vg_single_gated)(
            log_pi, log_A, log_obs, mask, gate_key, state_key
        )
        _assert_close(out, ref)

    def test_batch_padding(self, rng):
        """B not a lane multiple and > one tile."""
        args = _batch(rng, 130, 40, 4)
        out = self._run(args, t_chunk=16)
        _assert_close(out, _ref(*args))

    def test_masked_sparse_tayal_A_across_chunks(self, rng):
        """MASK_NEG hard-gated sparse transitions — the long-Tayal-
        window production shape — must stay finite and match the
        reference through the chunked kernel's per-chunk lse and
        exp-accumulation (the -1e30/clamp interplay the resident
        kernel's suite pins at small T)."""
        B, T, K = 4, 53, 4
        log_pi, log_A, log_obs, mask = _batch(rng, B, T, K)
        gate = jnp.asarray(rng.random((B, K, K)) < 0.4)
        log_A = jnp.where(gate, MASK_NEG, log_A)
        pi_gate = jnp.asarray(rng.random((B, K)) < 0.3)
        log_pi = jnp.where(pi_gate, safe_log(jnp.zeros(())), log_pi)
        out = self._run((log_pi, log_A, log_obs, mask), t_chunk=16)
        for o in out:
            assert np.all(np.isfinite(np.asarray(o)))
        _assert_close(out, _ref(log_pi, log_A, log_obs, mask))


class TestAlphaFused:
    """`kernels/alpha_fused.py`: the decode-phase filter op. The chunked
    forward's HBM alpha residual (interpreter mode) must equal the scan
    filter's per-step alpha, gated and ungated; and the CPU dispatch of
    forward_alpha must reproduce the materialized-kernel filter that
    `TayalHHMMLite.generated` previously ran."""

    def _residual(self, args, gate=None, t_chunk=16):
        # whitebox into the unified kernel module itself (not a shim):
        # the shared blocked forward + its padding/transpose plumbing
        from hhmm_tpu.kernels.pallas_semiring import (
            _LANES,
            _pad_chunked,
            _run_chunked_forward,
        )

        log_pi, log_A, log_obs, mask = args
        B, T, K = log_obs.shape
        gk, sk = gate if gate else (None, None)
        pi_t, A_t, obs_t, mask_t, gate_t, sk_t, Bp, Tp, nc = _pad_chunked(
            log_pi, log_A, log_obs, mask, gk, sk, t_chunk
        )
        ll, alpha_all = _run_chunked_forward(
            pi_t, A_t, obs_t, mask_t, gate_t, sk_t,
            (Bp // _LANES, nc), t_chunk, True,
        )
        return alpha_all.transpose(2, 0, 1)[:B, :T], ll[0, :B]

    def _scan_ref(self, args, gate=None):
        from hhmm_tpu.kernels.alpha_fused import _alpha_single

        g = gate if gate else ()
        return jax.vmap(lambda *a: _alpha_single(*a))(*args, *g)

    def test_residual_matches_scan(self, rng):
        args = _batch(rng, 5, 50, 4, ragged=True)
        la_k, ll_k = self._residual(args)
        la_r, ll_r = self._scan_ref(args)
        # padded (mask-0) steps carry alpha in both implementations
        np.testing.assert_allclose(
            np.asarray(la_k), np.asarray(la_r), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(ll_k), np.asarray(ll_r), rtol=1e-5
        )

    def test_residual_matches_scan_gated(self, rng):
        B, T, K = 4, 37, 4
        args = _batch(rng, B, T, K, ragged=True)
        gate = (
            jnp.asarray(rng.integers(0, 2, size=(B, T)), jnp.float32),
            jnp.asarray(
                np.tile((np.arange(K) % 2).astype(np.float32), (B, 1))
            ),
        )
        la_k, ll_k = self._residual(args, gate=gate)
        la_r, ll_r = self._scan_ref(args, gate=gate)
        np.testing.assert_allclose(
            np.asarray(la_k), np.asarray(la_r), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(ll_k), np.asarray(ll_r), rtol=1e-5
        )

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_generated_unchanged_on_cpu(self, rng):
        """TayalHHMMLite.generated (now routed through forward_alpha)
        must reproduce the materialized-kernel filter output exactly on
        the CPU dispatch path, both gate modes."""
        from hhmm_tpu.kernels import forward_filter
        from hhmm_tpu.models import TayalHHMMLite

        T, To = 60, 20
        x = jnp.asarray(rng.integers(0, 9, size=T + To), jnp.int32)
        sign = jnp.asarray(rng.integers(0, 2, size=T + To), jnp.int32)
        data = {
            "x": x[:T], "sign": sign[:T],
            "x_oos": x[T:], "sign_oos": sign[T:],
        }
        for mode in ("stan", "hard"):
            model = TayalHHMMLite(gate_mode=mode)
            theta = model.init_unconstrained(
                jax.random.PRNGKey(0),
                {k: np.asarray(v) for k, v in data.items()},
            )[None]
            out = model.generated(jnp.asarray(theta), data)

            params, _ = model.unpack(jnp.asarray(theta[0]))
            log_pi, log_A_t, log_obs = model._gated(
                params, data["x"], data["sign"]
            )
            la_ref, _ = forward_filter(log_pi, log_A_t, log_obs, None)
            np.testing.assert_allclose(
                np.asarray(out["alpha"][0]),
                np.asarray(jax.nn.softmax(la_ref, axis=-1)),
                rtol=1e-5,
                atol=1e-6,
                err_msg=mode,
            )
