"""Fault-injection + robustness suite (tier-1, fast — see
`docs/robustness.md`).

Proves each recovery path end-to-end with the `robust/faults.py`
injectors:

- in-scan guards: a NaN injected into one chain's gradient mid-scan
  leaves every other chain's posterior bit-identical to an uninjected
  run and marks exactly that chain unhealthy (NUTS, Gibbs; ChEES
  quarantines + stays finite — its adaptation is shared by design);
- self-healing dispatch: quarantined series are re-dispatched with
  re-jittered keys, healthy series kept bitwise, sticky faults degrade
  gracefully instead of crashing;
- crash recovery: a simulated crash between chunks + rerun resumes from
  the cache and matches the uninterrupted run bitwise; torn/corrupt
  cache entries are misses, not exceptions;
- diagnostics never raise or NaN on pathological draws;
- the static guard pass (`scripts/check_guards.py`) holds.
"""

import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hhmm_tpu.batch import ResultCache, digest_key, fit_batched
from hhmm_tpu.infer import (
    ChEESConfig,
    GibbsConfig,
    SamplerConfig,
    sample_chees_batched,
    sample_gibbs,
    sample_nuts,
)
from hhmm_tpu.infer.diagnostics import (
    ess,
    ess_many,
    split_rhat,
    split_rhat_many,
    summary,
)
from hhmm_tpu.models import MultinomialHMM
from hhmm_tpu.robust import FaultPlan, RetryPolicy, escalate, faults, rejitter
from hhmm_tpu.robust.guards import all_finite, finite_mask, guard_update

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _vg(q):
    """Standard-normal fused value-and-grad target."""
    return -0.5 * jnp.sum(q * q), -q


NUTS_CFG = SamplerConfig(
    num_warmup=25, num_samples=25, num_chains=3, max_treedepth=4, init_step_size=0.5
)

_NUTS_RUNS = {}  # plan -> run result (each run recompiles; cache for tier-1 speed)


def _run_nuts(plan):
    if plan not in _NUTS_RUNS:
        key = jax.random.PRNGKey(0)
        init = 0.1 * jax.random.normal(jax.random.PRNGKey(1), (3, 2))
        with faults.inject(plan):
            qs, stats = sample_nuts(None, key, init, NUTS_CFG, vg_fn=_vg)
        _NUTS_RUNS[plan] = (
            np.asarray(qs),
            {k: np.asarray(v) for k, v in stats.items()},
        )
    return _NUTS_RUNS[plan]


class TestGuardHelpers:
    def test_finite_mask_and_all_finite(self):
        assert bool(all_finite((jnp.ones(3), jnp.zeros(()))))
        assert not bool(all_finite((jnp.ones(3), jnp.asarray(np.nan))))
        # int leaves are ignored (cannot encode NaN)
        assert bool(all_finite(jnp.arange(3)))
        m = finite_mask(
            (jnp.asarray([[1.0, np.nan], [2.0, 3.0]]),), batch_ndim=1
        )
        np.testing.assert_array_equal(np.asarray(m), [False, True])

    def test_guard_update_freezes_permanently(self):
        healthy = jnp.asarray(True)
        state = (jnp.ones(2), jnp.asarray(0.0))
        bad = (jnp.full(2, np.nan), jnp.asarray(1.0))
        state1, healthy = guard_update(healthy, bad, state)
        assert not bool(healthy)
        np.testing.assert_array_equal(np.asarray(state1[0]), np.ones(2))
        # finite follow-up is still rejected: quarantine is permanent
        good = (jnp.full(2, 5.0), jnp.asarray(2.0))
        state2, healthy = guard_update(healthy, good, state1)
        assert not bool(healthy)
        np.testing.assert_array_equal(np.asarray(state2[0]), np.ones(2))


class TestNutsGuard:
    @pytest.mark.slow
    def test_nan_grad_mid_scan_quarantines_exactly_one_chain(self):
        """The acceptance-criteria scenario: NaN into one chain's
        gradient mid-scan -> all other chains bit-identical, exactly
        that chain unhealthy, its draws finite and frozen."""
        qs0, st0 = _run_nuts(FaultPlan(kind="nan_grad", step=-1, chain=-1))
        qs1, st1 = _run_nuts(FaultPlan(kind="nan_grad", step=30, chain=1))
        np.testing.assert_array_equal(st0["chain_healthy"], [True, True, True])
        np.testing.assert_array_equal(st0["quarantine_step"], [-1, -1, -1])
        np.testing.assert_array_equal(st1["chain_healthy"], [True, False, True])
        np.testing.assert_array_equal(st1["quarantine_step"], [-1, 30, -1])
        # other chains: bit-identical draws
        np.testing.assert_array_equal(qs1[[0, 2]], qs0[[0, 2]])
        # quarantined chain: all-finite, frozen at its last finite state
        # (global step 30 = sampling draw index 5; the guard rejects the
        # poisoned transition, so draw 5 repeats draw 4 and every draw
        # after stays frozen)
        assert np.isfinite(qs1[1]).all()
        assert (qs1[1, 5:] == qs1[1, 5]).all()
        np.testing.assert_array_equal(qs1[1, 5], qs1[1, 4])
        # pre-fault draws of the injected chain match the control
        np.testing.assert_array_equal(qs1[1, :5], qs0[1, :5])

    @pytest.mark.slow
    def test_noop_plan_is_bitwise_control(self):
        """A never-firing plan traces the same program as no plan at
        all AND produces identical draws — the control is honest."""
        qs0, st0 = _run_nuts(FaultPlan(kind="nan_grad", step=-1, chain=-1))
        key = jax.random.PRNGKey(0)
        init = 0.1 * jax.random.normal(jax.random.PRNGKey(1), (3, 2))
        qs_plain, _ = sample_nuts(None, key, init, NUTS_CFG, vg_fn=_vg)
        np.testing.assert_array_equal(qs0, np.asarray(qs_plain))

    def test_warmup_fault_quarantines(self):
        """A non-finite log-density during *warmup* also quarantines
        (adaptation state frozen with the chain). The remaining
        corruption kinds share this path and are unit-covered by
        TestCorruptKinds."""
        qs, st = _run_nuts(FaultPlan(kind="nan_logp", step=10, chain=2))
        np.testing.assert_array_equal(st["chain_healthy"], [True, True, False])
        assert st["quarantine_step"][2] == 10
        assert np.isfinite(qs).all()


class TestCorruptKinds:
    """Pure unit coverage of every in-scan corruption kind (the
    end-to-end guard path is exercised once per sampler above)."""

    def _arrays(self, kind, chain=1, step=5, n=3):
        with faults.inject(FaultPlan(kind=kind, step=step, chain=chain)):
            return faults.chain_fault_arrays(n)

    @pytest.mark.parametrize(
        "kind,field,expect",
        [
            ("nan_logp", "logp", np.isnan),
            ("inf_logp", "logp", np.isinf),
            ("nan_grad", "grad", np.isnan),
            ("nan_state", "q", np.isnan),
        ],
    )
    def test_each_kind_hits_only_its_target(self, kind, field, expect):
        fs, fk = self._arrays(kind)
        logp = jnp.zeros(3)
        grad = jnp.ones((3, 2))
        q = jnp.ones((3, 2))
        lo, gr, qo = faults.corrupt(jnp.asarray(5), fs, fk, logp, grad, q)
        out = {"logp": np.asarray(lo), "grad": np.asarray(gr), "q": np.asarray(qo)}
        assert expect(out[field][1]).all()
        # only chain 1 touched, and only the targeted field
        for name, arr in out.items():
            mask = np.zeros(3, bool)
            mask[1] = name == field
            bad = ~np.isfinite(arr.reshape(3, -1)).all(axis=1)
            np.testing.assert_array_equal(bad, mask)

    def test_wrong_step_is_noop(self):
        fs, fk = self._arrays("nan_grad", step=5)
        _, gr, _ = faults.corrupt(jnp.asarray(4), fs, fk, None, jnp.ones((3, 2)), None)
        assert np.isfinite(np.asarray(gr)).all()

    def test_corrupt_tree_nan_state(self):
        fs, fk = self._arrays("nan_state", chain=0)
        tree = {"a": jnp.ones((3, 2)), "n": jnp.arange(3)}  # int leaf untouched
        out = faults.corrupt_tree(jnp.asarray(5), fs, fk, tree)
        assert np.isnan(np.asarray(out["a"])[0]).all()
        assert np.isfinite(np.asarray(out["a"])[1:]).all()
        np.testing.assert_array_equal(np.asarray(out["n"]), np.arange(3))


class TestFaultPlanThreading:
    """The fault-plan stack is THREAD-LOCAL (the kernels/dispatch.py
    plan-scope discipline): a plan injected on one thread can never
    leak into another thread's fit/serve path."""

    def test_plan_never_leaks_across_threads(self):
        import threading

        ready = threading.Event()
        release = threading.Event()
        seen = {}

        def other_thread():
            # observed WHILE the main thread holds an active plan
            ready.wait(5)
            seen["active"] = faults.active()
            seen["traffic"] = faults.traffic_active()
            # and an injection HERE is invisible to the main thread
            with faults.inject(FaultPlan(kind="nan_grad", step=1, chain=0)):
                seen["own"] = faults.active()
                release.set()

        t = threading.Thread(target=other_thread)
        with faults.inject(
            faults.TrafficFaultPlan(device_loss_at_dispatch=0)
        ):
            with faults.inject(FaultPlan(kind="nan_logp", step=3, chain=1)):
                t.start()
                ready.set()
                release.wait(5)
                # the other thread's nan_grad plan must not shadow ours
                assert faults.active().kind == "nan_logp"
                assert faults.traffic_active().device_loss_at_dispatch == 0
        t.join()
        assert seen["active"] is None  # main thread's plans invisible
        assert seen["traffic"] is None
        assert seen["own"].kind == "nan_grad"
        # and after every scope exits, this thread is clean
        assert faults.active() is None and faults.traffic_active() is None

    def test_inner_plan_wins_per_type(self):
        with faults.inject(FaultPlan(kind="nan_logp", step=1, chain=0)):
            with faults.inject(
                faults.TrafficFaultPlan(slow_load_s=0.1, slow_load_every=1)
            ):
                with faults.inject(FaultPlan(kind="nan_grad", step=2, chain=1)):
                    # innermost of EACH type wins; types don't shadow
                    # each other
                    assert faults.active().kind == "nan_grad"
                    assert faults.traffic_active().slow_load_s == 0.1
                assert faults.active().kind == "nan_logp"
        assert faults.active() is None

    def test_inject_rejects_foreign_types(self):
        with pytest.raises(TypeError, match="FaultPlan"):
            with faults.inject({"kind": "nan_grad"}):
                pass


class TestCheesGuard:
    def test_nan_grad_quarantines_one_chain_of_one_series(self):
        def lp_bc(q):
            return -0.5 * jnp.sum(q * q, -1), -q

        cfg = ChEESConfig(num_warmup=20, num_samples=15, num_chains=2, max_leapfrogs=8)
        init = 0.3 * jax.random.normal(jax.random.PRNGKey(2), (2, 2, 3))
        with faults.inject(FaultPlan(kind="nan_grad", step=25, chain=0, series=1)):
            qs, st = sample_chees_batched(
                lp_bc, jax.random.PRNGKey(0), init, cfg, probe_vg=_vg
            )
        healthy = np.asarray(st["chain_healthy"])
        np.testing.assert_array_equal(healthy, [[True, True], [False, True]])
        assert np.asarray(st["quarantine_step"])[1, 0] == 25
        qs = np.asarray(qs)
        assert np.isfinite(qs).all()
        # frozen tail: global step 25 = sampling draw index 5
        assert (qs[1, 0, 5:] == qs[1, 0, 5]).all()


class TestGibbsGuard:
    def _data(self):
        rng = np.random.default_rng(0)
        return {"x": rng.integers(0, 3, size=60)}

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_nan_logp_quarantines_other_chain_bitwise(self):
        model = MultinomialHMM(K=2, L=3)
        cfg = GibbsConfig(num_warmup=5, num_samples=20, num_chains=2)
        data = self._data()
        with faults.inject(FaultPlan(kind="nan_logp", step=-1, chain=-1)):
            qs0, st0 = sample_gibbs(model, data, jax.random.PRNGKey(3), cfg)
        with faults.inject(FaultPlan(kind="nan_logp", step=12, chain=0)):
            qs1, st1 = sample_gibbs(model, data, jax.random.PRNGKey(3), cfg)
        np.testing.assert_array_equal(np.asarray(st0["chain_healthy"]), [True, True])
        np.testing.assert_array_equal(np.asarray(st1["chain_healthy"]), [False, True])
        np.testing.assert_array_equal(np.asarray(st1["quarantine_step"]), [12, -1])
        qs0, qs1 = np.asarray(qs0), np.asarray(qs1)
        # the other chain is bit-identical; the quarantined one stays
        # finite, frozen from the fault's record (t=12 -> draw index 7)
        np.testing.assert_array_equal(qs0[1], qs1[1])
        assert np.isfinite(qs1).all()
        assert (qs1[0, 7:] == qs1[0, 7]).all()
        # like the HMC samplers, the recorded logp trace is guarded (the
        # injected NaN records the last finite value; the event itself
        # is surfaced via quarantine_step, asserted above)
        assert np.isfinite(np.asarray(st1["logp"])).all()

    def test_nan_state_freezes_params(self):
        model = MultinomialHMM(K=2, L=3)
        cfg = GibbsConfig(num_warmup=5, num_samples=15, num_chains=1)
        with faults.inject(FaultPlan(kind="nan_state", step=8, chain=0)):
            qs, st = sample_gibbs(model, self._data(), jax.random.PRNGKey(4), cfg)
        assert not np.asarray(st["chain_healthy"])[0]
        assert np.isfinite(np.asarray(qs)).all()


class TestDiagnosticsRobust:
    def test_split_rhat_nonfinite_is_inf(self):
        x = np.random.default_rng(0).normal(size=(2, 40))
        x[0, 3] = np.nan
        assert split_rhat(x) == float("inf")
        x[0, 3] = np.inf
        assert split_rhat(x) == float("inf")

    def test_split_rhat_zero_variance_is_one(self):
        assert split_rhat(np.ones((2, 40))) == 1.0

    def test_ess_nonfinite_is_zero(self):
        x = np.random.default_rng(1).normal(size=(2, 64))
        x[1, 10] = np.nan
        assert ess(x) == 0.0

    def test_ess_zero_variance_is_nominal(self):
        assert ess(np.ones((2, 64))) == 4 * 32.0

    def test_many_variants_match_scalars_per_row(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(4, 2, 64))
        x[1, 0, 5] = np.nan  # non-finite row
        x[2] = 3.0  # zero-variance row
        r_many = split_rhat_many(x)
        e_many = ess_many(x)
        for i in range(4):
            assert r_many[i] == pytest.approx(split_rhat(x[i]), nan_ok=False)
            assert e_many[i] == pytest.approx(ess(x[i]), rel=1e-12)
        assert r_many[1] == float("inf") and e_many[1] == 0.0
        assert r_many[2] == 1.0 and np.isfinite(e_many).all()

    def test_summary_excludes_quarantined_chains(self):
        rng = np.random.default_rng(3)
        good = rng.normal(size=(1, 50))
        bad = np.full((1, 50), np.nan)
        samples = {"a": np.concatenate([good, bad])}
        out = summary(samples, health=np.array([True, False]))
        assert np.isfinite(out["a"]["mean"]).all()
        assert out["a"]["mean"][0] == pytest.approx(good.mean())
        assert out["a"]["chains_used"] == 1
        assert out["a"]["chains_quarantined"] == 1
        # all-quarantined: nothing dropped, flagged via chains_used=0
        out2 = summary(samples, health=np.array([False, False]))
        assert out2["a"]["chains_used"] == 0
        # no mask: unchanged legacy shape (no health keys)
        out3 = summary(samples)
        assert "chains_used" not in out3["a"]


class TestSafeLogsumexp:
    def test_values_and_grads(self):
        from hhmm_tpu.core.lmath import MASK_NEG, safe_logsumexp
        from jax.scipy.special import logsumexp as lse

        x = jnp.asarray([[0.5, -1.0, 2.0], [-np.inf, -np.inf, -np.inf]])
        out = safe_logsumexp(x, axis=-1)
        assert float(out[0]) == float(lse(x[0]))
        # default floor is -inf: likelihood ORDERING stays honest (an
        # impossible row ranks below any possible one)...
        assert float(out[1]) == -np.inf
        # ...while a finite floor is available for normalizer use
        assert float(safe_logsumexp(x, axis=-1, floor=MASK_NEG)[1]) == MASK_NEG
        # gradients: exact on live rows, exactly zero (never NaN) on
        # all-masked rows — for either floor
        for floor in (-np.inf, MASK_NEG):
            g = jax.grad(
                lambda v: jnp.nansum(
                    jnp.where(
                        jnp.isfinite(safe_logsumexp(v, axis=-1, floor=floor)),
                        safe_logsumexp(v, axis=-1, floor=floor),
                        0.0,
                    )
                )
            )(x)
            assert np.isfinite(np.asarray(g)).all()
            np.testing.assert_array_equal(np.asarray(g[1]), 0.0)
        g0 = jax.grad(lambda v: safe_logsumexp(v, axis=-1, floor=MASK_NEG).sum())(x)
        np.testing.assert_allclose(
            np.asarray(g0[0]), np.asarray(jax.grad(lambda v: lse(v))(x[0])), rtol=1e-6
        )

    def test_forward_filter_impossible_series_keeps_inf_ordering(self):
        """A series whose evidence is impossible under the model keeps
        loglik = -inf (NOT a finite floor: a finite value would outrank
        genuinely low log-likelihoods in model-comparison consumers like
        the Hassan likelihood-neighbor forecaster) — and never NaN.
        Gradients through the scan interior can still be non-finite for
        such fully-degenerate input; that is exactly what the in-scan
        chain-health guard quarantines (TestNutsGuard). The boundary
        guard's job is the zero cotangent into the all-masked reduction
        (test_values_and_grads)."""
        from hhmm_tpu.kernels.filtering import forward_filter

        log_pi = jnp.log(jnp.asarray([0.5, 0.5]))
        log_A = jnp.log(jnp.asarray([[0.7, 0.3], [0.4, 0.6]]))
        log_obs = jnp.full((4, 2), -jnp.inf)
        _, ll = forward_filter(log_pi, log_A, log_obs)
        assert float(ll) == -np.inf

    def test_smooth_empty_support_step_is_not_nan(self):
        """smooth() on a time step with empty posterior support keeps
        the -inf floor instead of NaN (guarded normalization)."""
        from hhmm_tpu.kernels.filtering import smooth

        la = jnp.asarray([[0.0, -1.0], [-jnp.inf, -jnp.inf]])
        lb = jnp.zeros((2, 2))
        g = np.asarray(smooth(la, lb))
        assert not np.isnan(g).any()
        assert np.isfinite(g[0]).all()


class TestRetryPolicy:
    def test_escalation_ladder_nuts(self):
        cfg = SamplerConfig(init_step_size=0.2, target_accept=0.8, max_treedepth=10)
        assert escalate(cfg, 1) == cfg  # fresh inits only
        c2 = escalate(cfg, 2)
        assert c2.init_step_size == pytest.approx(0.1)
        assert c2.target_accept == pytest.approx(0.85)
        assert c2.max_treedepth == 10
        c3 = escalate(cfg, 3)
        assert c3.init_step_size == pytest.approx(0.05)
        assert c3.max_treedepth == 8

    def test_escalation_ladder_chees_and_gibbs(self):
        cc = ChEESConfig(max_leapfrogs=16, init_step_size=0.1)
        c3 = escalate(cc, 3)
        assert c3.max_leapfrogs == 8 and c3.init_step_size == pytest.approx(0.025)
        # floors hold
        assert escalate(ChEESConfig(max_leapfrogs=8), 3).max_leapfrogs == 8
        assert escalate(SamplerConfig(max_treedepth=4), 3).max_treedepth == 4
        # Gibbs has no knobs: unchanged at every rung
        g = GibbsConfig()
        assert escalate(g, 3) == g

    def test_rejitter_deterministic_and_distinct(self):
        k = jax.random.PRNGKey(7)
        a1, a1b, a2 = rejitter(k, 1), rejitter(k, 1), rejitter(k, 2)
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a1b))
        assert not np.array_equal(np.asarray(a1), np.asarray(a2))
        assert not np.array_equal(np.asarray(a1), np.asarray(k))

    def test_backoff_schedule(self):
        p = RetryPolicy(backoff_base_s=2.0)
        assert [p.backoff(a) for a in range(3)] == [2.0, 4.0, 6.0]

    def test_ensure_backend_falls_back_to_cpu(self, monkeypatch):
        import hhmm_tpu.robust.retry as retry_mod

        calls = {"n": 0}
        real_devices = jax.devices

        def flaky(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("Unable to initialize backend 'tpu' (injected)")
            return real_devices()

        monkeypatch.setattr(retry_mod.jax, "devices", flaky)
        out = retry_mod.ensure_backend()
        assert out["fallback"] is True
        assert out["backend"] == "cpu"
        assert out["devices"] >= 1

    def test_ensure_backend_healthy_passthrough(self):
        from hhmm_tpu.robust.retry import ensure_backend

        out = ensure_backend()
        assert out["fallback"] is False
        assert out["devices"] >= 1


class TestBoundedRetry:
    """`robust/retry.py` BackoffPolicy + retry_call: the bounded
    second-chance ladder the pager's load path leans on."""

    def test_delay_schedule_deterministic_and_capped(self):
        from hhmm_tpu.robust.retry import BackoffPolicy

        p = BackoffPolicy(base_s=0.01, factor=2.0, max_s=0.02, jitter=0.0)
        assert [p.delay(a) for a in range(3)] == [0.01, 0.02, 0.02]
        j = BackoffPolicy(jitter=0.5)
        # deterministic for the same (seed, salt, attempt); jitter only
        # ever SHORTENS the raw delay (thundering-herd de-sync)
        assert j.delay(1, salt=7) == j.delay(1, salt=7)
        assert j.delay(1, salt=7) != j.delay(1, salt=8)
        raw = BackoffPolicy(jitter=0.0).delay(1)
        assert 0.5 * raw <= j.delay(1, salt=7) <= raw

    def test_retry_call_transient_heals(self):
        from hhmm_tpu.robust.retry import BackoffPolicy, retry_call

        calls, slept, noted = [], [], []
        def flaky():
            calls.append(1)
            return "ok" if len(calls) >= 3 else None
        out = retry_call(
            flaky,
            BackoffPolicy(attempts=3),
            sleep=slept.append,
            on_retry=lambda a, e: noted.append((a, e)),
        )
        assert out == "ok" and len(calls) == 3
        assert len(slept) == 2 and all(d > 0 for d in slept)
        assert [a for a, _ in noted] == [0, 1]

    def test_retry_call_budget_is_bounded(self):
        from hhmm_tpu.robust.retry import BackoffPolicy, retry_call

        calls = []
        out = retry_call(
            lambda: calls.append(1),  # always None: persistent failure
            BackoffPolicy(attempts=3),
            sleep=lambda d: None,
        )
        assert out is None and len(calls) == 3  # attempts = TOTAL calls

    def test_retry_call_exception_reraised_on_final_attempt(self):
        from hhmm_tpu.robust.retry import BackoffPolicy, retry_call

        calls = []
        def boom():
            calls.append(1)
            raise OSError("disk on fire")
        with pytest.raises(OSError):
            retry_call(boom, BackoffPolicy(attempts=2), sleep=lambda d: None)
        assert len(calls) == 2

    def test_retry_call_custom_failed_predicate(self):
        from hhmm_tpu.robust.retry import BackoffPolicy, retry_call

        seq = iter([-1, -1, 5])
        out = retry_call(
            lambda: next(seq),
            BackoffPolicy(attempts=3),
            failed=lambda r: r < 0,
            sleep=lambda d: None,
        )
        assert out == 5


class TestCacheRobust:
    def test_torn_file_is_miss_then_recomputable(self, tmp_path):
        cache = ResultCache(str(tmp_path))
        key = digest_key("torn")
        cache.put(key, {"a": np.arange(8.0)})
        path = os.path.join(str(tmp_path), f"{key}.npz")
        faults.tear_file(path, keep_bytes=16)
        assert cache.get(key) is None  # miss, not an exception
        assert not os.path.exists(path)  # quarantined aside
        cache.put(key, {"a": np.arange(8.0)})  # re-put works
        np.testing.assert_array_equal(cache.get(key)["a"], np.arange(8.0))

    def test_garbage_and_empty_files_are_misses(self, tmp_path):
        cache = ResultCache(str(tmp_path))
        for key, payload in [(digest_key("g"), b"not a zip at all"), (digest_key("e"), b"")]:
            with open(os.path.join(str(tmp_path), f"{key}.npz"), "wb") as f:
                f.write(payload)
            assert cache.get(key) is None

    def test_atomic_put_leaves_no_temp_files(self, tmp_path):
        cache = ResultCache(str(tmp_path))
        cache.put(digest_key("x"), {"a": np.eye(3)})
        leftovers = [p for p in os.listdir(str(tmp_path)) if ".tmp" in p]
        assert leftovers == []


@pytest.fixture
def multinom_setup():
    rng = np.random.default_rng(0)
    xs = rng.integers(0, 3, size=(4, 50))
    model = MultinomialHMM(K=2, L=3)
    cfg = GibbsConfig(num_warmup=5, num_samples=15, num_chains=1)
    return model, xs, cfg


class TestFitCrashResume:
    @pytest.mark.slow
    def test_crash_between_chunks_resumes_bitwise(self, multinom_setup, tmp_path, capsys):
        """Satellite: chunked dispatch resuming after a simulated crash
        between chunks — completed chunks are cache hits, and the
        resumed posteriors match an uninterrupted run bitwise."""
        model, xs, cfg = multinom_setup
        d_ref, d_crash = str(tmp_path / "ref"), str(tmp_path / "crash")
        qs_ref, _ = fit_batched(
            model, {"x": xs}, jax.random.PRNGKey(0), cfg, chunk_size=2, cache_dir=d_ref
        )
        with pytest.raises(faults.SimulatedCrash):
            with faults.inject(FaultPlan(crash_after_chunks=1)):
                fit_batched(
                    model, {"x": xs}, jax.random.PRNGKey(0), cfg,
                    chunk_size=2, cache_dir=d_crash,
                )
        # chunk 1 (+ the init entry) survived the crash on disk
        assert len([f for f in os.listdir(d_crash) if f.endswith(".npz")]) == 2
        capsys.readouterr()
        qs2, st2 = fit_batched(
            model, {"x": xs}, jax.random.PRNGKey(0), cfg,
            chunk_size=2, cache_dir=d_crash,
        )
        out = capsys.readouterr().out
        assert "chunk 1/2: cache hit" in out
        assert "chunk 2/2: computed + cached" in out
        np.testing.assert_array_equal(np.asarray(qs_ref), np.asarray(qs2))
        assert np.asarray(st2["chain_healthy"]).all()


class TestSelfHealing:
    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_quarantined_series_redisptached_healthy_kept_bitwise(
        self, multinom_setup, tmp_path
    ):
        model, xs, cfg = multinom_setup
        xs = xs[:2]
        qs_clean, _ = fit_batched(
            model, {"x": xs}, jax.random.PRNGKey(0), cfg, chunk_size=2
        )
        with faults.inject(FaultPlan(kind="unhealthy_result", series=1, chain=0, step=3)):
            qs, st = fit_batched(
                model, {"x": xs}, jax.random.PRNGKey(0), cfg,
                chunk_size=2, cache_dir=str(tmp_path),
            )
        assert np.asarray(st["chain_healthy"]).all()  # healed
        assert np.isfinite(np.asarray(qs)).all()
        # the untouched series is bitwise the clean result; the healed
        # one was re-dispatched with re-jittered keys (different draws)
        np.testing.assert_array_equal(np.asarray(qs[0]), np.asarray(qs_clean[0]))
        assert not np.array_equal(np.asarray(qs[1]), np.asarray(qs_clean[1]))
        # the cache holds the healed result: a rerun reproduces it
        qs_r, st_r = fit_batched(
            model, {"x": xs}, jax.random.PRNGKey(0), cfg,
            chunk_size=2, cache_dir=str(tmp_path),
        )
        np.testing.assert_array_equal(np.asarray(qs), np.asarray(qs_r))
        assert np.asarray(st_r["chain_healthy"]).all()

    def test_device_retries_zero_still_runs_once(self, multinom_setup):
        """A no-device-retries policy executes the dispatch exactly once
        instead of skipping it (regression: empty retry loop)."""
        model, xs, cfg = multinom_setup
        qs, st = fit_batched(
            model, {"x": xs[:2]}, jax.random.PRNGKey(0), cfg,
            chunk_size=2, retry=RetryPolicy(device_retries=0),
        )
        assert qs.shape[0] == 2
        assert np.asarray(st["chain_healthy"]).all()

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_sticky_fault_degrades_gracefully(self, multinom_setup, capsys):
        """A series that cannot be healed is returned with its mask
        down after the bounded ladder — the sweep completes."""
        model, xs, cfg = multinom_setup
        xs = xs[:2]
        with faults.inject(
            FaultPlan(kind="unhealthy_result", series=0, chain=0, step=3, sticky=True)
        ):
            qs, st = fit_batched(
                model, {"x": xs}, jax.random.PRNGKey(0), cfg, chunk_size=2,
                retry=RetryPolicy(max_heal_attempts=2),
            )
        healthy = np.asarray(st["chain_healthy"])
        assert not healthy[0].all() and healthy[1].all()
        out = capsys.readouterr().out
        assert "healing attempt" in out and "still quarantined" in out


class TestCheckGuardsScript:
    def test_repo_passes(self, check_guards_repo):
        proc = check_guards_repo  # one shared repo scan (conftest)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ok" in proc.stdout

    def test_bare_except_and_unguarded_sampler_flagged(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        (pkg / "infer").mkdir(parents=True)
        (pkg / "bad.py").write_text("try:\n    pass\nexcept:\n    pass\n")
        (pkg / "infer" / "run.py").write_text("def sample_nuts():\n    pass\n")
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "check_guards.py"),
                str(tmp_path),
            ],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1
        assert "bare `except:`" in proc.stdout
        assert "chain-health guard" in proc.stdout


class TestBenchCpuFallback:
    # the end-to-end subprocess smoke is minutes of jax import + compile,
    # so it rides in the slow lane; the fallback decision logic itself is
    # covered fast by TestRetryPolicy::test_ensure_backend_falls_back_to_cpu
    @pytest.mark.slow
    def test_bench_quick_exits_zero_with_backend_record(self):
        """`python bench.py` on a TPU-less host must exit 0 and emit a
        JSON record carrying the backend/fallback fields (the
        BENCH_r05.json crash mode, fixed)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--quick"],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        rec = json.loads(proc.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "tayal_batched_posterior_throughput"
        assert rec["backend"] == "cpu"
        assert rec["backend_fallback"] is False  # cpu probe succeeded
        assert rec["value"] > 0
