"""Jangmin (2004) market-regime application (apps/jangmin.py) — the
replication the reference abandoned for lack of its semisup Stan model,
run end to end here: simulate → price path → MA-gradient k-means labels
→ semi-supervised TreeHMM fit of the 63-leaf hierarchy → regime
recovery."""

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute suites; fast subset: -m 'not slow'

from hhmm_tpu.apps.jangmin import (
    N_REGIMES,
    fit_market,
    ma_gradient_labels,
    simulate_market,
)
from hhmm_tpu.infer import SamplerConfig


class TestSimulateAndLabel:
    def test_simulate_shapes(self, rng):
        m = simulate_market(300, rng)
        assert m["x"].shape == m["price"].shape == (300,)
        assert m["regime"].min() >= 0 and m["regime"].max() < N_REGIMES
        assert np.all(m["price"] > 0)

    def test_ma_gradient_labels_order(self, rng):
        """Labels must be ordered by drift: mean return under label 4
        (strong bull) above label 0 (strong bear)."""
        m = simulate_market(2000, rng)
        g = ma_gradient_labels(m["price"])
        assert g.shape == m["x"].shape
        assert set(np.unique(g)) <= set(range(N_REGIMES))
        mean_low = m["x"][g == 0].mean()
        mean_high = m["x"][g == N_REGIMES - 1].mean()
        assert mean_high > mean_low

    def test_labels_track_true_regimes(self, rng):
        """The k-means labeling is the reference's level-1 supervision
        heuristic. Regimes overlap and switch fast (mean leaf runs of a
        few steps vs a 5-step MA), so absolute agreement is inherently
        modest — the check is informativeness: agreement above the
        label-marginal shuffle baseline."""
        m = simulate_market(2000, rng)
        g = ma_gradient_labels(m["price"])
        agree = (g == m["regime"]).mean()
        p_true = np.bincount(m["regime"], minlength=N_REGIMES) / len(g)
        p_lab = np.bincount(g, minlength=N_REGIMES) / len(g)
        shuffle_base = float((p_true * p_lab).sum())
        assert agree > shuffle_base + 0.02, (agree, shuffle_base)

    def test_short_series_raises(self, rng):
        with pytest.raises(ValueError, match="window"):
            ma_gradient_labels(np.ones(4))


class TestFit:
    def test_semisup_fit_recovers_regimes(self, rng):
        """Jangmin regimes are intrinsically confusable per step — the
        TRUE parameters' unsupervised decode is the ceiling (≈26% at
        T=250; regimes share overlapping leaf distributions, which is
        presumably why the reference abandoned the replication). The
        gate: a healthy sampler on the 202-parameter tree posterior
        whose unsupervised decode beats the majority-class rate and is
        not materially below the oracle ceiling."""
        import jax.numpy as jnp

        from hhmm_tpu.hhmm.examples import jangmin2004_tree
        from hhmm_tpu.models import TreeHMM

        m = simulate_market(250, rng)
        cfg = SamplerConfig(
            num_warmup=100, num_samples=100, num_chains=1, max_treedepth=5
        )
        fit = fit_market(
            m["x"], m["regime"], config=cfg, key=jax.random.PRNGKey(3),
            regime_true=m["regime"],
        )
        assert float(np.asarray(fit.stats["diverging"]).mean()) < 0.15
        assert np.isfinite(np.asarray(fit.samples)).all()

        # oracle ceiling: unsupervised decode at the true parameters
        oracle = TreeHMM(jangmin2004_tree(), semisup=False, order_mu="none")
        theta_true = jnp.asarray(oracle.pack(oracle.spec_params()))[None, None, :]
        gen = oracle.generated(theta_true, {"x": jnp.asarray(m["x"])})
        gamma = np.asarray(gen["gamma"])[0, 0]
        groups = np.asarray(oracle.groups)
        rp = np.stack([gamma[:, groups == r].sum(1) for r in range(N_REGIMES)], 1)
        oracle_acc = float((rp.argmax(1) == m["regime"]).mean())

        majority = np.bincount(m["regime"]).max() / len(m["regime"])
        assert fit.accuracy is not None
        assert fit.accuracy > majority, (fit.accuracy, majority)
        assert fit.accuracy > oracle_acc - 0.05, (fit.accuracy, oracle_acc)
