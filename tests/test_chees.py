"""ChEES-HMC sampler tests (`infer/chees.py`).

The reference has exactly one inference engine (Stan NUTS); ChEES-HMC is
this framework's batch-native alternative — fixed jittered trajectory
lengths shared across chains, adapted from cross-chain statistics
(Hoffman, Radul & Sountsov 2021). Validation mirrors the discipline used
for the NUTS path (SURVEY.md §4): exact-moment checks on a tractable
target, cross-sampler posterior agreement on a real model, and SBC rank
uniformity through the batched engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import kstest

from hhmm_tpu.batch import fit_batched
from hhmm_tpu.infer import ChEESConfig, SamplerConfig, sample_chees, sample_nuts
from hhmm_tpu.infer.chees import halton_base2
from hhmm_tpu.models import MultinomialHMM
from hhmm_tpu.sim import hmm_sim, obsmodel_categorical


class TestHalton:
    def test_van_der_corput_prefix(self):
        np.testing.assert_allclose(
            halton_base2(7), [0.5, 0.25, 0.75, 0.125, 0.625, 0.375, 0.875]
        )

    def test_range_and_spread(self):
        u = halton_base2(256)
        assert (u > 0).all() and (u < 1).all()
        # low-discrepancy: every dyadic interval of width 1/8 gets 32 points
        counts, _ = np.histogram(u, bins=8, range=(0, 1))
        assert (counts == 32).all()


class TestGaussianTarget:
    def test_moments_correlated_gaussian(self):
        """Exact target: correlated 4-D Gaussian. Posterior moments from
        pooled chains must match to MC error."""
        rng = np.random.default_rng(0)
        L = np.tril(rng.normal(size=(4, 4)) * 0.5) + np.eye(4)
        cov = L @ L.T
        prec = jnp.asarray(np.linalg.inv(cov), jnp.float32)
        mu = jnp.asarray([1.0, -2.0, 0.5, 3.0], jnp.float32)

        def logp(q):
            d = q - mu
            return -0.5 * d @ prec @ d

        cfg = ChEESConfig(num_warmup=300, num_samples=500, num_chains=8)
        init = jax.random.normal(jax.random.PRNGKey(1), (8, 4)) * 2.0
        qs, stats = sample_chees(logp, jax.random.PRNGKey(0), init, cfg)
        s = np.asarray(qs).reshape(-1, 4)
        np.testing.assert_allclose(s.mean(0), np.asarray(mu), atol=0.15)
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.35)
        assert float(np.asarray(stats["diverging"]).mean()) == 0.0
        # adaptation actually ran: trajectory moved off its 1.0 init and
        # stays under the leapfrog cap
        traj = float(stats["traj_length"])
        eps = float(stats["step_size"])
        assert traj != pytest.approx(cfg.init_traj_length)
        assert traj <= eps * cfg.max_leapfrogs + 1e-6

    def test_requires_two_chains(self):
        with pytest.raises(ValueError, match=">=2 chains"):
            sample_chees(
                lambda q: -0.5 * jnp.sum(q * q),
                jax.random.PRNGKey(0),
                jnp.zeros((1, 2)),
                ChEESConfig(num_chains=1),
            )



@pytest.mark.slow
class TestCrossSamplerAgreement:
    def test_matches_nuts_on_multinomial_hmm(self, rng):
        """ChEES and NUTS target the identical posterior; their
        posterior means over pooled chains must agree to MC error.
        Label-symmetry is broken by sorting states on phi[:, 0] per
        draw (as in the SBC suite)."""
        K, L, T = 2, 3, 300
        model = MultinomialHMM(K=K, L=L)
        A = np.array([[0.85, 0.15], [0.25, 0.75]])
        p1 = np.array([0.6, 0.4])
        phi = np.array([[0.7, 0.2, 0.1], [0.1, 0.3, 0.6]])
        z, x = hmm_sim(
            jax.random.PRNGKey(5), T, A, p1, obsmodel_categorical(phi), validate=False
        )
        data = {
            "x": np.asarray(x, np.int32)[None],
            "mask": np.ones((1, T), np.float32),
        }

        def pooled_canonical_means(qs):
            draws = model.constrained_draws(qs.reshape(-1, qs.shape[-1]))
            phid = np.asarray(draws["phi_k"]).reshape(-1, K, L)
            Ad = np.asarray(draws["A_ij"]).reshape(-1, K, K)
            order = np.argsort(phid[:, :, 0], axis=1)
            idx = np.arange(phid.shape[0])[:, None]
            phid = phid[idx, order]
            Ad = Ad[idx[:, :, None], order[:, :, None], order[:, None, :]]
            return np.concatenate([phid.mean(0).ravel(), Ad.mean(0).ravel()])

        chees_cfg = ChEESConfig(num_warmup=250, num_samples=400, num_chains=4)
        nuts_cfg = SamplerConfig(
            num_warmup=250, num_samples=400, num_chains=4, max_treedepth=6
        )
        qs_c, st_c = fit_batched(model, data, jax.random.PRNGKey(0), chees_cfg, chunk_size=1)
        qs_n, st_n = fit_batched(model, data, jax.random.PRNGKey(0), nuts_cfg, chunk_size=1)
        assert float(np.asarray(st_c["diverging"]).mean()) < 0.05
        m_c = pooled_canonical_means(qs_c[0])
        m_n = pooled_canonical_means(qs_n[0])
        np.testing.assert_allclose(m_c, m_n, atol=0.06)


class TestRaggedChunk:
    @pytest.mark.slow
    def test_ragged_final_chunk_runs_and_pools_weighted(self, rng):
        """B not divisible by chunk_size: the final chunk is padded by
        repeating the last series; those duplicates carry zero weight in
        the pooled shared-adaptation statistics (batch/fit.py chunk_w).
        The run must produce finite draws for every real series."""
        K, L, T = 2, 3, 120
        model = MultinomialHMM(K=K, L=L)
        B = 3
        xs = []
        for i in range(B):
            A = rng.dirichlet(np.ones(K), size=K)
            phi = rng.dirichlet(np.ones(L), size=K)
            _, x = hmm_sim(
                jax.random.PRNGKey(i),
                T,
                A,
                rng.dirichlet(np.ones(K)),
                obsmodel_categorical(phi),
                validate=False,
            )
            xs.append(np.asarray(x, np.int32))
        data = {"x": np.stack(xs), "mask": np.ones((B, T), np.float32)}
        cfg = ChEESConfig(num_warmup=50, num_samples=50, num_chains=2)
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(0), cfg, chunk_size=2)
        assert qs.shape[:3] == (B, 2, 50)
        assert np.isfinite(np.asarray(qs)).all()
        assert np.isfinite(np.asarray(stats["logp"])).all()



@pytest.mark.slow
class TestAppHarnesses:
    """The walk-forward application harnesses accept a ChEESConfig and
    route both the batched fit and (Hassan) the warm-start pilot through
    the shared-adaptation sampler."""

    def test_hassan_wf_forecast_chees(self, tmp_path):
        from hhmm_tpu.apps.hassan import simulate_ohlc, wf_forecast

        rng = np.random.default_rng(5)
        ohlc = simulate_ohlc(rng, T=120, vol=0.008, regimes=1, drift_spread=-0.02)
        res = wf_forecast(
            ohlc,
            train_len=110,
            K=2,
            L=2,
            config=ChEESConfig(num_warmup=100, num_samples=100, num_chains=2),
            cache_dir=str(tmp_path),
            chunk_size=16,
        )
        assert res.forecasts.shape[0] == 10
        assert np.isfinite(res.point).all()
        assert res.diverged.mean() < 0.2
        assert res.errors["mape"] < 10.0
        assert res.errors["r2"] > 0.3  # tracks the trending level

    def test_tayal_wf_trade_chees(self, tmp_path, tayal_wf_tasks):
        from hhmm_tpu.apps.tayal import wf_trade

        results = wf_trade(
            tayal_wf_tasks,
            config=ChEESConfig(num_warmup=80, num_samples=80, num_chains=2),
            chunk_size=4,
            cache_dir=str(tmp_path),
        )
        assert len(results) == 4
        for r in results:
            assert r.diverged < 0.2
            assert np.isfinite(r.bnh).all()


class TestSBCChEES:
    @pytest.mark.parametrize("max_leapfrogs", [256, 16])
    @pytest.mark.slow
    def test_rank_uniformity_multinomial(self, rng, max_leapfrogs):
        """SBC through the batched engine with the ChEES sampler: ranks
        of prior draws among posterior draws must be uniform (the same
        gate as tests/test_sbc.py, chains=4). ``max_leapfrogs=16`` is
        the benchmark default (bench.py) — this is its calibration
        evidence; 256 is the unconstrained sampler."""
        K, L, T = 2, 3, 250
        N_REPS, THIN = 8, 4
        model = MultinomialHMM(K=K, L=L)
        datasets, trues = [], []
        for _ in range(N_REPS):
            p1 = rng.dirichlet(np.ones(K))
            A = rng.dirichlet(np.ones(K), size=K)
            phi = rng.dirichlet(np.ones(L), size=K)
            z, x = hmm_sim(
                jax.random.PRNGKey(int(rng.integers(1 << 30))),
                T,
                A,
                p1,
                obsmodel_categorical(phi),
                validate=False,
            )
            datasets.append({"x": np.asarray(x, np.int32), "mask": np.ones(T, np.float32)})
            trues.append((p1, A, phi))
        data = {k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]}
        cfg = ChEESConfig(
            num_warmup=150, num_samples=200, num_chains=4, max_leapfrogs=max_leapfrogs
        )
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(2), cfg, chunk_size=N_REPS)
        assert float(np.asarray(stats["diverging"]).mean()) < 0.1

        units = []
        for i in range(N_REPS):
            draws = model.constrained_draws(qs[i].reshape(-1, qs.shape[-1]))
            p1d = np.asarray(draws["p_1k"]).reshape(-1, K)
            Ad = np.asarray(draws["A_ij"]).reshape(-1, K, K)
            phid = np.asarray(draws["phi_k"]).reshape(-1, K, L)
            order = np.argsort(phid[:, :, 0], axis=1)
            idx = np.arange(p1d.shape[0])[:, None]
            p1d = np.take_along_axis(p1d, order, axis=1)
            phid = phid[idx, order]
            Ad = Ad[idx[:, :, None], order[:, :, None], order[:, None, :]]
            p1, A, phi = trues[i]
            torder = np.argsort(phi[:, 0])
            truth = np.array(
                [
                    p1[torder][0],
                    A[torder][:, torder][0, 0],
                    A[torder][:, torder][1, 1],
                    phi[torder][0, 0],
                    phi[torder][1, 0],
                ]
            )
            flat = np.column_stack(
                [p1d[:, 0], Ad[:, 0, 0], Ad[:, 1, 1], phid[:, 0, 0], phid[:, 1, 0]]
            )
            thinned = flat[::THIN]
            r = (thinned < truth[None, :]).sum(axis=0)
            units.append((r + 0.5) / (thinned.shape[0] + 1))
        u = np.concatenate(units)
        assert 0.30 < u.mean() < 0.70, f"rank mean {u.mean():.3f}"
        p = kstest(u, "uniform").pvalue
        assert p > 1e-3, f"KS uniformity p={p:.2e}"
