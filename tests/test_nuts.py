"""Correctness tests of the iterative NUTS sampler on known targets."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hhmm_tpu.infer import sample_nuts, SamplerConfig, split_rhat, ess
from hhmm_tpu.infer.run import warmup_schedule


def test_warmup_schedule_shapes():
    for W in [50, 150, 500, 1000]:
        upd, wend = warmup_schedule(W)
        assert upd.shape == (W,) and wend.shape == (W,)
        assert bool(np.asarray(wend).any())


def test_standard_normal_moments():
    dim = 4

    def logp(q):
        return -0.5 * jnp.sum(q * q)

    cfg = SamplerConfig(num_warmup=500, num_samples=1500, num_chains=4)
    qs, stats = sample_nuts(logp, jax.random.PRNGKey(0), jnp.zeros(dim), cfg)
    qs = np.asarray(qs)  # [chains, draws, dim]
    assert qs.shape == (4, 1500, dim)
    assert np.asarray(stats["diverging"]).mean() < 0.01
    np.testing.assert_allclose(qs.mean(axis=(0, 1)), 0.0, atol=0.1)
    np.testing.assert_allclose(qs.std(axis=(0, 1)), 1.0, atol=0.1)
    for d in range(dim):
        assert split_rhat(qs[:, :, d]) < 1.02
        assert ess(qs[:, :, d]) > 500


def test_correlated_gaussian():
    """2-D Gaussian with strong correlation — exercises the U-turn criterion."""
    cov = np.array([[1.0, 0.95], [0.95, 1.0]])
    prec = jnp.asarray(np.linalg.inv(cov))

    def logp(q):
        return -0.5 * q @ prec @ q

    cfg = SamplerConfig(num_warmup=600, num_samples=2000, num_chains=4)
    qs, stats = sample_nuts(logp, jax.random.PRNGKey(1), jnp.zeros(2), cfg)
    qs = np.asarray(qs).reshape(-1, 2)
    emp_cov = np.cov(qs.T)
    np.testing.assert_allclose(emp_cov, cov, atol=0.15)
    # trajectories must be longer than 1 step for this target
    assert np.asarray(stats["num_leaves"]).mean() > 3


def test_scaled_gaussian_mass_adaptation():
    """Badly-scaled target: mass-matrix adaptation must pick up the scales."""
    scales = jnp.asarray([0.1, 1.0, 10.0])

    def logp(q):
        return -0.5 * jnp.sum((q / scales) ** 2)

    cfg = SamplerConfig(num_warmup=600, num_samples=1500, num_chains=2)
    qs, stats = sample_nuts(logp, jax.random.PRNGKey(2), jnp.zeros(3), cfg)
    qs = np.asarray(qs)
    np.testing.assert_allclose(
        qs.std(axis=(0, 1)), np.asarray(scales), rtol=0.15
    )
    # adapted inverse mass ≈ marginal variances
    inv_mass = np.asarray(stats["inv_mass"])[0]
    np.testing.assert_allclose(inv_mass, np.asarray(scales) ** 2, rtol=0.6)


def test_divergence_detection():
    """A grossly-too-large step on a stiff Gaussian must flag divergence
    (divergences are the reference's model-misfit signal, log.md:397-437)."""
    from hhmm_tpu.infer.nuts import nuts_step

    def logp(q):
        return -0.5 * jnp.sum((q / 0.01) ** 2)

    vg = jax.value_and_grad(logp)
    q = jnp.full((3,), 0.05)
    lp, g = vg(q)
    _, _, _, info = nuts_step(
        vg, jax.random.PRNGKey(0), q, lp, g,
        jnp.asarray(5.0), jnp.ones(3), max_treedepth=6,
    )
    assert bool(info.diverging)


def test_treedepth_bounded():
    """Flat target: trajectory must stop at max_treedepth leaves, not hang."""
    from hhmm_tpu.infer.nuts import nuts_step

    def logp(q):
        return jnp.sum(q) * 1e-6  # nearly flat — never U-turns

    vg = jax.value_and_grad(logp)
    q = jnp.zeros(2)
    lp, g = vg(q)
    _, _, _, info = nuts_step(
        vg, jax.random.PRNGKey(0), q, lp, g,
        jnp.asarray(0.5), jnp.ones(2), max_treedepth=5,
    )
    assert int(info.depth) == 5
    assert int(info.num_leaves) <= 2**5 - 1
