"""Regime-event feed tests (`serve/events.py`) and its scheduler /
request-stanza integration.

The feed is an analytics SUBSCRIPTION on the tick path, so the serve
degrade discipline is the headline contract: observation and drain shed
(counted, swallowed), never raise; queues are bounded per tenant with
drop-oldest; detach forgets detector state but keeps queued events.
The integration test is the acceptance scenario: a 256-series mixed
HMM+HSMM replay through two schedulers sharing one bucket ladder and
one feed stays compile-flat after warmup, drains >= 1 event per tenant,
and escapes zero exceptions.
"""

import numpy as np

from hhmm_tpu.models import GaussianHMM, GaussianHSMM
from hhmm_tpu.obs.request import RequestRecorder
from hhmm_tpu.serve import (
    MicroBatchScheduler,
    PosteriorSnapshot,
    RegimeEvent,
    RegimeEventFeed,
    model_spec,
)


def _flip_probs(regime, K=2, p=0.95):
    out = np.full(K, (1.0 - p) / (K - 1))
    out[regime] = p
    return out


class TestFeedUnit:
    def test_flip_events_publish_and_drain_per_tenant(self):
        feed = RegimeEventFeed(hold=2, drift_threshold=None)
        for t in range(4):  # regime 0, committed at hold=2
            feed.observe("a", "tenA", _flip_probs(0), -1.0)
            feed.observe("b", "tenB", _flip_probs(0), -1.0)
        for t in range(4):  # flip to regime 1
            feed.observe("a", "tenA", _flip_probs(1), -1.0)
            feed.observe("b", "tenB", _flip_probs(1), -1.0)
        assert feed.queued("tenA") >= 1 and feed.queued("tenB") >= 1
        evs_a = feed.drain(tenant="tenA")
        assert evs_a and all(isinstance(e, RegimeEvent) for e in evs_a)
        assert all(e.tenant == "tenA" and e.kind == "flip" for e in evs_a)
        assert evs_a[-1].regime == 1
        assert feed.queued("tenA") == 0 and feed.queued("tenB") >= 1
        rest = feed.drain()
        assert rest and all(e.tenant == "tenB" for e in rest)
        st = feed.stanza()
        assert st["errors"] == 0
        assert st["tenants"]["tenA"]["drained"] == len(evs_a)
        assert st["tenants"]["tenB"]["queued"] == 0

    def test_queue_cap_drops_oldest(self):
        feed = RegimeEventFeed(hold=1, drift_threshold=None, queue_cap=3)
        # alternate every tick at hold=1: a flip per observation after
        # the first commit
        for t in range(10):
            feed.observe("s", "ten", _flip_probs(t % 2), -1.0)
        assert feed.queued("ten") == 3
        evs = feed.drain(tenant="ten")
        assert len(evs) == 3
        st = feed.stanza()["tenants"]["ten"]
        assert st["dropped"] == st["published"] - 3
        assert st["dropped"] > 0
        # the survivors are the NEWEST events
        assert evs[-1].tick == 10

    def test_drift_alarm_and_generation_restart(self):
        feed = RegimeEventFeed(
            hold=3, drift_threshold=4.0, drift_rate=0.1, drift_calibrate=8
        )
        ll = 0.0
        for t in range(30):  # steady per-tick increments: calibration
            ll += -1.0
            assert feed.observe("s", "ten", _flip_probs(0), ll, generation=0) == []
        # a generation bump with a big level jump must NOT alarm: the
        # differencing baseline restarts instead of seeing a -500 step
        ll2 = -500.0
        evs = feed.observe("s", "ten", _flip_probs(0), ll2, generation=1)
        assert evs == []
        for t in range(5):
            ll2 += -1.0
            evs = feed.observe("s", "ten", _flip_probs(0), ll2, generation=1)
            assert all(e.kind != "drift" for e in evs)
        # within-generation collapse of the increments DOES alarm
        drifted = []
        for t in range(20):
            ll2 += -9.0
            drifted += feed.observe("s", "ten", _flip_probs(0), ll2, generation=1)
        assert any(e.kind == "drift" for e in drifted)

    def test_observe_sheds_never_raises(self):
        feed = RegimeEventFeed(hold=1)
        base = feed.stanza()["errors"]
        # garbage inputs: non-numeric loglik trips inside the lock
        assert feed.observe("s", "ten", _flip_probs(0), "not-a-float") == []
        assert feed.stanza()["errors"] == base + 1
        # NaN / wrong-rank probs are skipped silently (no flip state),
        # not errors
        assert feed.observe("s", "ten", np.array([np.nan, 1.0]), -1.0) == []
        assert feed.observe("s", "ten", np.zeros((2, 2)), -1.0) == []
        # a broken detector inside the locked section is counted too
        feed.observe("s2", "ten", _flip_probs(0), -1.0)
        feed._series["s2"].detector.update = None  # type: ignore[assignment]
        assert feed.observe("s2", "ten", _flip_probs(0), -1.0) == []
        assert feed.stanza()["errors"] >= base + 2

    def test_drain_sheds_never_raises(self):
        feed = RegimeEventFeed(hold=1, drift_threshold=None)
        for t in range(4):
            feed.observe("s", "ten", _flip_probs(t % 2), -1.0)
        feed._queues = None  # type: ignore[assignment]  # sabotage
        assert feed.drain() == []
        feed._queues = {}  # restore so the accounting read works
        assert feed.stanza()["errors"] >= 1

    def test_forget_keeps_queued_events(self):
        feed = RegimeEventFeed(hold=1, drift_threshold=None)
        for t in range(4):
            feed.observe("s", "ten", _flip_probs(t % 2), -1.0)
        n = feed.queued("ten")
        assert n > 0
        feed.forget("s")
        assert feed.stanza()["series_tracked"] == 0
        assert feed.queued("ten") == n  # events survive detach

    def test_series_cap_lru(self):
        feed = RegimeEventFeed(hold=1, drift_threshold=None, series_cap=4)
        for i in range(10):
            feed.observe(f"s{i}", "ten", _flip_probs(0), -1.0)
        assert feed.stanza()["series_tracked"] == 4


def _packed_snapshot(model, params, n_draws=2):
    q = np.asarray(model.pack(params), np.float32)
    return PosteriorSnapshot(
        spec=model_spec(model),
        draws=np.repeat(q[None], n_draws, axis=0),
        healthy=True,
    )


class TestSchedulerIntegration:
    def test_mixed_hmm_hsmm_replay_compile_flat_events_per_tenant(self):
        """The acceptance scenario: 256 series split across a plain
        GaussianHMM and a duration-expanded GaussianHSMM, served by two
        schedulers sharing one bucket ladder and ONE event feed, driven
        through a mid-replay regime break. Post-warmup both schedulers
        are compile-flat, every tenant drains >= 1 RegimeEvent, and no
        response carries an error."""
        feed = RegimeEventFeed(hold=2, margin=0.0, drift_threshold=None)
        buckets = (8, 128)  # the SHARED ladder
        n_ten = 8
        hmm = GaussianHMM(K=2)
        hsmm = GaussianHSMM(K=2, Dmax=4)
        p_hmm = {
            "p_1k": np.array([0.5, 0.5]),
            "A_ij": np.array([[0.95, 0.05], [0.05, 0.95]]),
            "mu_k": np.array([-2.0, 2.0]),
            "sigma_k": np.array([1.0, 1.0]),
        }
        p_hsmm = dict(
            p_hmm, dur_kd=np.full((2, 4), 0.25)
        )
        scheds = {}
        for tag, model, params in (
            ("hmm", hmm, p_hmm), ("hsmm", hsmm, p_hsmm)
        ):
            snap = _packed_snapshot(model, params)
            sched = MicroBatchScheduler(model, buckets=buckets, events=feed)
            rejected = sched.attach_many(
                [
                    (f"{tag}-{i}", snap, None, f"ten{i % n_ten}")
                    for i in range(128)
                ]
            )
            assert rejected == []
            scheds[tag] = sched
        rng = np.random.default_rng(0)
        T = 12

        def replay(t):
            level = -2.0 if t < T // 2 else 2.0  # the regime break
            out = []
            for tag, sched in scheds.items():
                for i in range(128):
                    sched.submit(
                        f"{tag}-{i}",
                        {"x": level + 0.1 * rng.standard_normal()},
                    )
                out.extend(sched.flush())
            return out

        for t in range(2):  # warmup: init + update kernels compile
            for r in replay(t):
                assert r.error is None
        warm = {tag: s.metrics.compile_count for tag, s in scheds.items()}
        for t in range(2, T):
            for r in replay(t):
                assert r.error is None
                assert not r.degraded
        for tag, sched in scheds.items():
            assert sched.metrics.compile_count == warm[tag], tag
        evs = feed.drain()
        by_tenant = {}
        for e in evs:
            by_tenant.setdefault(e.tenant, []).append(e)
        assert set(by_tenant) == {f"ten{i}" for i in range(n_ten)}
        assert all(len(v) >= 1 for v in by_tenant.values())
        # expanded-state responses were collapsed before detection:
        # flips are regime indices, not count-down lanes
        assert all(
            e.regime is not None and e.regime < 2
            for e in evs if e.kind == "flip"
        )
        assert feed.stanza()["errors"] == 0

    def test_detach_forgets_feed_state(self):
        feed = RegimeEventFeed(hold=1, drift_threshold=None)
        model = GaussianHMM(K=2)
        params = {
            "p_1k": np.array([0.5, 0.5]),
            "A_ij": np.array([[0.9, 0.1], [0.1, 0.9]]),
            "mu_k": np.array([-1.0, 1.0]),
            "sigma_k": np.array([0.8, 0.8]),
        }
        sched = MicroBatchScheduler(
            model, buckets=(4,), events=feed
        )
        sched.attach("s0", _packed_snapshot(model, params), tenant="tenX")
        for t in range(3):
            sched.submit("s0", {"x": (-1.0) ** t})
            assert all(r.error is None for r in sched.flush())
        assert feed.stanza()["series_tracked"] == 1
        assert sched.detach("s0")
        assert feed.stanza()["series_tracked"] == 0


class TestRequestStanza:
    def test_events_block_and_render(self):
        rec = RequestRecorder(enabled=True)
        st = rec.stanza()
        assert "events" in st and st["events"] is None  # shape-stable
        rec.note_event("tenA", "flip")
        rec.note_event("tenA", "drift")
        rec.note_event("tenB", "flip")
        st = rec.stanza()
        ev = st["events"]
        assert ev["flips"] == 2 and ev["drifts"] == 1
        assert ev["tenants"]["tenA"] == {"flips": 1, "drifts": 1}
        # key order: the events block sits between scheduler and
        # pipeline (stanza diffing tools key on stable ordering)
        keys = list(st)
        assert keys.index("scheduler") < keys.index("events") < keys.index(
            "pipeline"
        )
        import io

        from scripts.obs_report import render_request

        buf = io.StringIO()
        render_request({"request": st}, buf)
        out = buf.getvalue()
        assert "regime events" in out and "2 flips" in out
        assert "tenA" in out
        rec.reset_window()
        assert rec.stanza()["events"] is None

    def test_scheduler_notes_events_to_recorder(self):
        feed = RegimeEventFeed(hold=1, drift_threshold=None)
        rec = RequestRecorder(enabled=True)
        model = GaussianHMM(K=2)
        params = {
            "p_1k": np.array([0.5, 0.5]),
            "A_ij": np.array([[0.9, 0.1], [0.1, 0.9]]),
            "mu_k": np.array([-2.0, 2.0]),
            "sigma_k": np.array([0.7, 0.7]),
        }
        sched = MicroBatchScheduler(
            model, buckets=(4,), events=feed, recorder=rec
        )
        sched.attach("s0", _packed_snapshot(model, params), tenant="tenZ")
        for t in range(6):
            sched.submit("s0", {"x": -2.0 if t < 3 else 2.0})
            assert all(r.error is None for r in sched.flush())
        ev = rec.stanza()["events"]
        assert ev is not None and ev["flips"] >= 1
        assert "tenZ" in ev["tenants"]
