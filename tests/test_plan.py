"""Execution-planner suite (`hhmm_tpu/plan/`, `docs/sharding.md`).

Pins the planner's contracts:

- **golden decisions**: the joint (mesh axes, chunk, buckets, branch)
  choice is frozen on fixed topologies — a planner change that moves a
  layout must move these tests consciously;
- **parity**: planned execution matches the single-device reference
  across {1, 2, 4, 8}-device CPU meshes — BITWISE for filter outputs,
  draw-for-draw for FFBS, and bitwise for the planner-driven
  ``fit_batched`` (ragged final chunk and masked padding included);
- **one substrate**: `scripts/check_guards.py` invariant 7 — no
  ``Mesh``/``NamedSharding``/``PartitionSpec`` construction outside
  ``hhmm_tpu/plan/`` and ``core/compat.py`` (positive/negative
  fixtures);
- **bench**: ``bench.py --plan-sweep --quick`` emits a gateable
  ``tayal_plan_sweep_throughput`` record with a ``plan`` manifest
  stanza and bitwise parity across topologies.

The 8 virtual CPU devices come from `tests/conftest.py`
(``xla_force_host_platform_device_count``), the same substrate
``__graft_entry__.dryrun_multichip`` and ``bench.py --plan-sweep`` use.
"""

import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hhmm_tpu.batch import fit_batched, pad_datasets
from hhmm_tpu.infer import GibbsConfig
from hhmm_tpu.kernels import ffbs_dispatch, forward_filter
from hhmm_tpu.kernels import dispatch as kdispatch
from hhmm_tpu.models import TayalHHMM
from hhmm_tpu.obs import manifest as obs_manifest
from hhmm_tpu.plan import Plan, WorkloadShape, make_plan, plan_for_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOPOLOGIES = (1, 2, 4, 8)


def _devices(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} virtual devices")
    return devs[:n]


class TestPlannerGolden:
    """Frozen layout decisions on fixed topologies."""

    @pytest.mark.parametrize(
        "shape, D, axes, chunk, branch",
        [
            # plenty of series: 1-D series mesh, chunk already aligned
            ((256, 1024, 1, 4), 8, (("series", 8),), 64, "scan"),
            # chains divide the topology exactly -> chain axis first
            ((8, 32, 2, 4), 8, (("series", 4), ("chain", 2)), 8, "scan"),
            # single long series: every device sequence-shards
            ((1, 128, 1, 4), 8, (("sp", 8),), 1, "seqshard"),
            # few series, long T: the joint 2-D series x sp mesh
            ((2, 64, 1, 4), 8, (("series", 2), ("sp", 4)), 2, "seqshard"),
            # indivisible T: leftover devices idle, recorded in reason
            ((5, 77, 1, 4), 8, (("series", 4),), 4, "scan"),
            # one device: no mesh at all
            ((64, 1024, 1, 4), 1, (), 64, "scan"),
        ],
    )
    def test_decisions_frozen(self, shape, D, axes, chunk, branch):
        B, T, C, K = shape
        p = make_plan(
            WorkloadShape(B=B, T=T, C=C, K=K),
            n_devices=D,
            chunk_size=64 if B > 8 else 3 if B == 5 else B,
            platform="cpu",
        )
        assert p.axes == axes
        assert p.branch == branch
        if B == 5:  # the auto-round case: chunk 3 -> 4 on a 4-way series axis
            assert (p.chunk_requested, p.chunk) == (3, 4)
        else:
            assert p.chunk == chunk

    def test_chunk_autoround_and_buckets(self):
        p = make_plan(
            WorkloadShape(B=10, T=64), n_devices=8, chunk_size=6, platform="cpu"
        )
        assert p.series_ways == 8
        assert (p.chunk_requested, p.chunk) == (6, 8)
        # serve ladder: every bucket a series-ways multiple
        assert all(b % 8 == 0 for b in p.buckets)
        assert p.shard_min_bucket == 32  # 4 lanes per device
        assert "rounded up" in p.reason

    def test_forced_layouts(self):
        shape = WorkloadShape(B=4, T=64, C=2)
        naive = make_plan(shape, n_devices=8, layout="series", platform="cpu")
        assert naive.axes == (("series", 8),)
        single = make_plan(shape, n_devices=8, layout="single", platform="cpu")
        assert single.axes == () and single.mesh is None
        with pytest.raises(ValueError, match="layout"):
            make_plan(shape, n_devices=8, layout="bogus", platform="cpu")

    def test_stanza_golden(self):
        p = make_plan(
            WorkloadShape(B=2, T=64, C=1, K=4), n_devices=8, chunk_size=2,
            platform="cpu",
        )
        st = p.stanza()
        assert st["mesh"] == {"series": 2, "sp": 4}
        assert st["specs"]["data"] == ["series"]
        assert st["chunk"] == 2 and st["branch"] == "seqshard"
        assert st["devices"] == 8 and st["devices_used"] == 8
        assert isinstance(st["reason"], str) and "sp=4" in st["reason"]
        json.dumps(st)  # must be JSON-clean for manifests

    def test_duration_expands_state_width_not_digest(self):
        """The HSMM expansion factor (`models/hsmm.py` Dmax): branch
        resolution sees state_width = K * duration, while as_dict —
        the manifest-digest surface — emits `duration` ONLY when > 1,
        so every pre-HSMM workload digest is unchanged."""
        plain = WorkloadShape(B=4, T=64, K=3)
        assert plain.state_width == 3
        assert "duration" not in plain.as_dict()
        exp = WorkloadShape(B=4, T=64, K=3, duration=8)
        assert exp.state_width == 24
        assert exp.as_dict()["duration"] == 8
        assert plain.as_dict() == {"B": 4, "T": 64, "C": 1, "K": 3}
        # the plan resolves its branch at the EXPANDED width: a plan
        # for (K=3, duration=8) is the plan for a plain K=24 chain
        p_exp = make_plan(exp, n_devices=1, platform="cpu")
        p_wide = make_plan(
            WorkloadShape(B=4, T=64, K=24), n_devices=1, platform="cpu"
        )
        assert p_exp.branch == p_wide.branch
        json.dumps(p_exp.stanza())

    def test_stanza_noted_in_manifests(self):
        p = make_plan(
            WorkloadShape(B=3, T=32), n_devices=4, chunk_size=3, platform="cpu"
        )
        assert obs_manifest.noted_stanza("plan") == p.stanza()
        man = obs_manifest.collect_manifest(config={"T": 32})
        assert man["plan"] == p.stanza()
        stz = obs_manifest.manifest_stanza(config={"T": 32})
        assert stz["plan"] == p.stanza()

    def test_plan_for_mesh_wraps_and_autorounds(self):
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(_devices(4)), ("series",))
        p = plan_for_mesh(
            mesh, WorkloadShape(B=6, T=48), chunk_size=3, platform="cpu"
        )
        assert p.axes == (("series", 4),)
        assert (p.chunk_requested, p.chunk) == (3, 4)
        assert p.mesh is mesh  # the caller's mesh is reused, not rebuilt
        bad = Mesh(np.asarray(_devices(2)), ("sp",))
        with pytest.raises(ValueError, match="series"):
            plan_for_mesh(bad, WorkloadShape(B=4, T=32))

    def test_sharding_tolerates_absent_axes(self):
        p = make_plan(
            WorkloadShape(B=8, T=32, C=1), devices=_devices(4), chunk_size=8,
            platform="cpu",
        )
        sh = p.sharding("series", "chain", None)  # no chain axis: replicated
        assert sh is not None and sh.spec == ("series", None, None)

    def test_dispatch_scope_pins_auto(self):
        p = make_plan(
            WorkloadShape(B=4, T=32, K=4), n_devices=1, platform="cpu"
        )
        assert p.branch == "scan"  # CPU crossover table: scan everywhere
        with kdispatch.plan_time_parallel(True):
            assert kdispatch.use_assoc(4, 32) is True
            # explicit call-site settings still beat the plan scope
            assert kdispatch.use_assoc(4, 32, time_parallel=False) is False
        with p.dispatch_scope():
            assert kdispatch.use_assoc(4, 32) is False
        assert kdispatch.use_assoc(4, 32) is False  # scope restored


def _random_hmm_batch(rng, B, T, K):
    log_pi = jnp.log(jnp.asarray(rng.dirichlet(np.ones(K), size=B), jnp.float32))
    log_A = jnp.log(jnp.asarray(rng.dirichlet(np.ones(K), size=(B, K)), jnp.float32))
    log_obs = jnp.asarray(rng.normal(size=(B, T, K)) - 1.0, jnp.float32)
    return log_pi, log_A, log_obs


class TestPlannedKernelParity:
    """Planned (sharded) kernel execution vs the single-device
    reference: bitwise for the filter, draw-for-draw for FFBS, across
    every topology — the correctness bar every plan must clear."""

    @pytest.mark.parametrize("n", TOPOLOGIES)
    def test_forward_filter_bitwise(self, rng, n, masked=False):
        devs = _devices(n)
        B, T, K = 8, 40, 4
        log_pi, log_A, log_obs = _random_hmm_batch(rng, B, T, K)
        mask = (
            jnp.asarray((rng.uniform(size=(B, T)) > 0.25).astype(np.float32))
            if masked
            else jnp.ones((B, T), jnp.float32)
        )
        fn = lambda lp, lA, lo, m: jax.vmap(forward_filter)(lp, lA, lo, m)
        a_ref, ll_ref = jax.jit(fn)(log_pi, log_A, log_obs, mask)
        plan = make_plan(
            WorkloadShape(B=B, T=T, C=1, K=K), devices=devs, chunk_size=B
        )
        if plan.mesh is None:
            planned = jax.jit(fn)
        else:
            sh = plan.data_sharding
            planned = jax.jit(fn, in_shardings=(sh(2), sh(3), sh(3), sh(2)))
        a, ll = planned(log_pi, log_A, log_obs, mask)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a_ref))
        np.testing.assert_array_equal(np.asarray(ll), np.asarray(ll_ref))

    @pytest.mark.parametrize("n", (2, 8))
    def test_forward_filter_bitwise_masked(self, rng, n):
        # ragged-T via masked padding: the padded tail must be a no-op
        # under the planned layout exactly as on one device
        self.test_forward_filter_bitwise(rng, n, masked=True)

    @pytest.mark.parametrize("n", TOPOLOGIES)
    def test_ffbs_draw_for_draw(self, rng, n):
        devs = _devices(n)
        B, T, K = 8, 48, 4
        log_pi, log_A, log_obs = _random_hmm_batch(rng, B, T, K)
        keys = jax.random.split(jax.random.PRNGKey(11), B)
        fn = lambda k, lp, lA, lo: jax.vmap(ffbs_dispatch)(k, lp, lA, lo)
        z_ref, ll_ref = jax.jit(fn)(keys, log_pi, log_A, log_obs)
        plan = make_plan(
            WorkloadShape(B=B, T=T, C=1, K=K), devices=devs, chunk_size=B
        )
        if plan.mesh is None:
            planned = jax.jit(fn)
        else:
            sh = plan.data_sharding
            planned = jax.jit(fn, in_shardings=(sh(2), sh(2), sh(3), sh(3)))
        with plan.dispatch_scope():
            z, ll = planned(keys, log_pi, log_A, log_obs)
        np.testing.assert_array_equal(np.asarray(z), np.asarray(z_ref))
        np.testing.assert_array_equal(np.asarray(ll), np.asarray(ll_ref))


class TestPlannedFitParity:
    """Planner-driven ``fit_batched`` vs the single-device path —
    the acceptance bar: a >=4-device CPU mesh, ragged final chunk
    (B=6 over chunk 4), masked (ragged-T) padding, chunk auto-rounding
    (8-device plan rounds the chunk up and pads the whole batch)."""

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_fit_matches_single_device(self):
        from __graft_entry__ import _tayal_batch

        model = TayalHHMM(gate_mode="hard")
        B = 6
        rng = np.random.default_rng(5)
        lengths = [40, 48, 44, 48, 40, 36]  # ragged T per series
        xs, ss = _tayal_batch(B, 48, seed=9)
        datasets = [
            {"x": np.asarray(xs[i][: lengths[i]]), "sign": np.asarray(ss[i][: lengths[i]])}
            for i in range(B)
        ]
        data = pad_datasets(datasets, time_keys=["x", "sign"])
        cfg = GibbsConfig(num_warmup=3, num_samples=5, num_chains=1)
        key = jax.random.PRNGKey(0)

        qs_ref, st_ref = fit_batched(model, data, key, cfg, chunk_size=4)

        # 4-device plan: chunk 4 stays, B=6 leaves a ragged final chunk
        plan4 = make_plan(
            WorkloadShape(B=B, T=48, C=1, K=model.K),
            devices=_devices(4),
            chunk_size=4,
        )
        assert plan4.chunk == 4
        qs4, st4 = fit_batched(model, data, key, cfg, plan=plan4)
        np.testing.assert_array_equal(np.asarray(qs4), np.asarray(qs_ref))
        np.testing.assert_array_equal(
            np.asarray(st4["logp"]), np.asarray(st_ref["logp"])
        )

        # 8-device single-axis plan: chunk auto-rounds 4 -> 8, which
        # exceeds B=6 — the whole batch dispatches as one padded chunk
        plan8 = make_plan(
            WorkloadShape(B=B, T=48, C=1, K=model.K),
            devices=_devices(8),
            chunk_size=4,
            layout="series",
        )
        assert (plan8.chunk_requested, plan8.chunk) == (4, 8)
        qs8, _ = fit_batched(model, data, key, cfg, plan=plan8)
        np.testing.assert_array_equal(np.asarray(qs8), np.asarray(qs_ref))

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_legacy_mesh_autorounds_instead_of_raising(self):
        """The old `chunk_size not divisible by mesh series axis`
        ValueError is gone: the planner rounds the chunk up and the fit
        still matches the unsharded path."""
        from jax.sharding import Mesh

        from __graft_entry__ import _tayal_batch

        model = TayalHHMM(gate_mode="hard")
        B = 4
        xs, ss = _tayal_batch(B, 32, seed=2)
        data = {"x": np.asarray(xs), "sign": np.asarray(ss)}
        cfg = GibbsConfig(num_warmup=2, num_samples=4, num_chains=1)
        mesh = Mesh(np.asarray(_devices(4)), ("series",))
        qs_m, _ = fit_batched(
            model, data, jax.random.PRNGKey(1), cfg, chunk_size=3, mesh=mesh
        )
        qs_ref, _ = fit_batched(
            model, data, jax.random.PRNGKey(1), cfg, chunk_size=4
        )
        np.testing.assert_array_equal(np.asarray(qs_m), np.asarray(qs_ref))

    def test_explicit_plan_chain_mismatch_raises(self):
        """A plan built for a different chain count must fail with a
        planner-level message, not an opaque XLA sharding error."""
        model = TayalHHMM(gate_mode="hard")
        plan = make_plan(
            WorkloadShape(B=4, T=8, C=4), n_devices=8, platform="cpu"
        )
        assert plan.ways("chain") == 4
        with pytest.raises(ValueError, match="num_chains"):
            fit_batched(
                model,
                {"x": np.zeros((4, 8), np.int32), "sign": np.zeros((4, 8), np.int32)},
                jax.random.PRNGKey(0),
                GibbsConfig(num_warmup=1, num_samples=1, num_chains=3),
                plan=plan,
            )

    def test_plan_and_mesh_are_exclusive(self):
        model = TayalHHMM(gate_mode="hard")
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(_devices(2)), ("series",))
        plan = make_plan(WorkloadShape(B=2, T=8), devices=_devices(2))
        with pytest.raises(ValueError, match="not both"):
            fit_batched(
                model,
                {"x": np.zeros((2, 8), np.int32), "sign": np.zeros((2, 8), np.int32)},
                jax.random.PRNGKey(0),
                GibbsConfig(num_warmup=1, num_samples=1, num_chains=1),
                mesh=mesh,
                plan=plan,
            )


class TestSchedulerPlanned:
    """Planner-driven serving: plan-chosen bucket ladder, sharded flush
    for large buckets — responses bitwise-match the unsharded scheduler
    and the compile count stays flat after warmup."""

    def test_sharded_flush_parity_and_compile_flat(self):
        from __graft_entry__ import _tayal_batch
        from hhmm_tpu.serve import (
            MicroBatchScheduler,
            PosteriorSnapshot,
            model_spec,
        )

        model = TayalHHMM(gate_mode="hard")
        B, T = 16, 5
        x, sign = _tayal_batch(B, T, seed=3)
        x, sign = np.asarray(x), np.asarray(sign)
        rng = np.random.default_rng(0)
        draws = (rng.normal(size=(4, model.n_free)) * 0.3).astype(np.float32)
        snap = PosteriorSnapshot(spec=model_spec(model), draws=draws, healthy=True)

        plan = make_plan(
            WorkloadShape(B=B, T=T, C=1, K=model.K),
            devices=_devices(4),
            buckets=(4, 16),
        )
        assert plan.buckets == (4, 16)
        assert plan.shard_bucket(16) and not plan.shard_bucket(4)

        def replay(sched, t):
            for i in range(B):
                sched.submit(f"s{i}", {"x": int(x[i, t]), "sign": int(sign[i, t])})
            return {r.series_id: r for r in sched.flush()}

        ref = MicroBatchScheduler(model, buckets=(4, 16))
        ref.attach_many([(f"s{i}", snap, None) for i in range(B)])
        planned = MicroBatchScheduler(model, plan=plan)  # planner ladder
        planned.attach_many([(f"s{i}", snap, None) for i in range(B)])
        assert planned.buckets == (4, 16)
        for t in range(2):
            rr, rp = replay(ref, t), replay(planned, t)
            for k in rr:
                np.testing.assert_array_equal(rr[k].probs, rp[k].probs)
                assert rr[k].loglik == rp[k].loglik
        warm = planned.metrics.compile_count
        assert warm > 0
        for t in range(2, T):
            rr, rp = replay(ref, t), replay(planned, t)
            for k in rr:
                np.testing.assert_array_equal(rr[k].probs, rp[k].probs)
        assert planned.metrics.compile_count == warm  # flat after warmup


class TestPlanSweepBench:
    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_quick_sweep_record(self):
        """`bench.py --plan-sweep --quick` must exit 0 with bitwise
        parity across topologies and emit the gateable
        tayal_plan_sweep_throughput record whose manifest carries the
        plan stanza (the tier-1 acceptance gate)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--plan-sweep", "--quick"],
            capture_output=True,
            text=True,
            timeout=600,
            cwd=REPO,
            env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        rec = json.loads(out.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "tayal_plan_sweep_throughput"
        assert rec["unit"] == "series/sec"
        assert rec["parity_ok"] is True
        assert rec["manifest"]["plan"]["mesh"] is not None
        assert rec["manifest"]["plan"]["branch"] in ("scan", "assoc", "seqshard")
        multi = [p for p in rec["points"] if p["devices"] > 1]
        assert multi, "sweep must cover a multi-device topology"
        for p in multi:
            assert p["parity_bitwise"] is True
            assert p["plan"]["mesh"] is not None
            assert p["naive_series_per_sec"] > 0
        assert rec["points"][0]["devices"] == 1  # the parity reference


class TestCheckGuardsInvariant7:
    def _run_on(self, tmp_path):
        return subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "check_guards.py"),
                str(tmp_path),
            ],
            capture_output=True,
            text=True,
        )

    def test_mesh_construction_flagged(self, tmp_path):
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "rogue.py").write_text(
            "from jax.sharding import Mesh\n\n"
            "def f(devs):\n    return Mesh(devs, ('series',))\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "constructs `Mesh`" in proc.stdout

    def test_aliased_partition_spec_flagged(self, tmp_path):
        # the aliased spelling must trip too, or the check is evaded
        pkg = tmp_path / "hhmm_tpu"
        pkg.mkdir()
        (pkg / "rogue.py").write_text(
            "from jax.sharding import PartitionSpec as P\n\nspec = P('series')\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "constructs `PartitionSpec`" in proc.stdout

    def test_attribute_spelling_flagged(self, tmp_path):
        (tmp_path / "hhmm_tpu").mkdir()
        (tmp_path / "bench.py").write_text(
            "import jax.sharding\n\n"
            "def f(mesh):\n    return jax.sharding.NamedSharding(mesh, None)\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "constructs `NamedSharding`" in proc.stdout
        assert "bench.py" in proc.stdout

    def test_planner_and_compat_are_allowed(self, tmp_path):
        plan_dir = tmp_path / "hhmm_tpu" / "plan"
        plan_dir.mkdir(parents=True)
        (plan_dir / "planner.py").write_text(
            "from jax.sharding import Mesh, NamedSharding, PartitionSpec\n\n"
            "def build(devs):\n"
            "    return NamedSharding(Mesh(devs, ('series',)), PartitionSpec('series'))\n"
        )
        core = tmp_path / "hhmm_tpu" / "core"
        core.mkdir(parents=True)
        (core / "compat.py").write_text(
            "def pspec(*axes):\n"
            "    from jax.sharding import PartitionSpec\n"
            "    return PartitionSpec(*axes)\n"
        )
        proc = self._run_on(tmp_path)
        assert "constructs" not in proc.stdout, proc.stdout

    def test_repo_passes(self, check_guards_repo):
        proc = check_guards_repo  # one shared repo scan (conftest)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "placement objects confined" in proc.stdout
