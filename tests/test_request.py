"""Request plane suite (`hhmm_tpu/obs/request.py` + the scheduler
wiring, tier-1, fast — see docs/observability.md "request plane").

Pins the PR's contracts:

- **lifecycle decomposition**: a completed TickTrace's queue/form/
  device/post shares sum exactly to its total; a trace missing a stage
  decomposes to None (never a bogus share);
- **recorder**: tenant attribution, windowed percentiles with the
  `obs/trace.py` stride decimation, queue-depth accounting, fairness
  spread (None until two tenants), tenant-cardinality bound, disabled
  mode truly off;
- **scheduler integration**: default tenant = series is behavior-
  preserving, per-tenant quota sheds the offending tenant only,
  tenant-labeled shed counters on the shared plane, stanza shares
  present after a served replay, compile count flat with the recorder
  on;
- **invariant 10** (check_guards): raw perf_counter reads under
  hhmm_tpu/serve/ are flagged, request-plane clock reads pass;
- **staleness across detach -> pager re-attach** (ISSUE 10 satellite):
  the gauge drops a detached series and restarts its age on page-in.
"""

import os
import subprocess
import sys
import time

import numpy as np
import jax
import pytest

from hhmm_tpu.models import MultinomialHMM
from hhmm_tpu.obs import metrics as obs_metrics
from hhmm_tpu.obs import request as obs_request
from hhmm_tpu.obs.request import RequestRecorder, TickTrace
from hhmm_tpu.serve import (
    AdmissionPolicy,
    MicroBatchScheduler,
    PosteriorSnapshot,
    ServeMetrics,
    SnapshotPager,
    SnapshotRegistry,
    model_spec,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fake_snapshot(model, n_draws=4, scale=0.3, seed=0):
    rng = np.random.default_rng(seed)
    draws = (rng.normal(size=(n_draws, model.n_free)) * scale).astype(
        np.float32
    )
    return PosteriorSnapshot(spec=model_spec(model), draws=draws)


class _Clock:
    """Deterministic injectable clock."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestTickTrace:
    def test_decomposition_sums_to_total(self):
        tr = TickTrace("s", "t", 1.0)
        tr.t_admit, tr.t_dispatch, tr.t_device, tr.t_respond = (
            1.5,
            1.7,
            2.6,
            2.65,
        )
        d = tr.decompose()
        assert d["queue_s"] == pytest.approx(0.5)
        assert d["device_s"] == pytest.approx(0.9)
        assert d["total_s"] == pytest.approx(
            d["queue_s"] + d["form_s"] + d["device_s"] + d["post_s"]
        )

    def test_partial_lifecycle_decomposes_none(self):
        tr = TickTrace("s", "t", 1.0)
        tr.t_respond = 2.0  # shed: never admitted/dispatched
        assert tr.decompose() is None

    def test_bucket_stamp_splits_formation(self):
        tr = TickTrace("s", "t", 1.0)
        tr.t_admit, tr.t_bucket, tr.t_dispatch = 1.5, 1.6, 1.9
        tr.t_device, tr.t_respond = 2.0, 2.1
        d = tr.decompose()
        assert d["assign_s"] == pytest.approx(0.1)
        assert d["stack_s"] == pytest.approx(0.3)
        assert d["form_s"] == pytest.approx(d["assign_s"] + d["stack_s"])


class TestRecorder:
    def _complete(self, rec, clock, tenant, queue_s, device_s, n=1):
        """Drive n full lifecycles with controlled stage durations."""
        for _ in range(n):
            tr = rec.enqueue("s-" + tenant, tenant)
            clock.t += queue_s
            rec.admit([tr])
            rec.stage([tr], "bucket")
            clock.t += 0.001  # form
            rec.stage([tr], "dispatch")
            clock.t += device_s
            rec.stage([tr], "device")
            clock.t += 0.001  # post
            rec.complete_group([tr], kernel="update", bucket=8)
        rec.flush_done()

    def test_tenant_attribution_and_shares(self):
        clock = _Clock()
        rec = RequestRecorder(enabled=True, window_s=60.0, clock=clock)
        self._complete(rec, clock, "a", queue_s=0.010, device_s=0.030, n=5)
        self._complete(rec, clock, "b", queue_s=0.200, device_s=0.030, n=5)
        st = rec.stanza()
        a, b = st["tenants"]["a"], st["tenants"]["b"]
        assert a["ticks"] == b["ticks"] == 5
        # tenant b is queue-dominated, a is device-dominated
        assert b["queue_share"] > 0.8 > b["device_share"]
        assert a["device_share"] > a["queue_share"]
        # shares partition the total
        for row in (a, b, st["overall"]):
            assert (
                row["queue_share"] + row["device_share"] + row["other_share"]
            ) == pytest.approx(1.0, abs=0.01)
        # fairness spread = p99 gap between the two tenants (ms)
        assert st["fairness"]["p99_spread_ms"] == pytest.approx(190.0, abs=5.0)
        assert st["fairness"]["flushes"] == 2

    def test_spread_none_until_two_tenants(self):
        clock = _Clock()
        rec = RequestRecorder(enabled=True, clock=clock)
        assert rec.p99_spread_ms() is None
        self._complete(rec, clock, "solo", 0.01, 0.01)
        assert rec.p99_spread_ms() is None
        self._complete(rec, clock, "duo", 0.01, 0.01)
        assert rec.p99_spread_ms() is not None

    def test_windowed_not_lifetime(self):
        """Old samples age out of the percentile window: long-lived
        serving reports CURRENT health, not lifetime averages."""
        clock = _Clock()
        rec = RequestRecorder(enabled=True, window_s=10.0, clock=clock)
        self._complete(rec, clock, "a", queue_s=5.0, device_s=0.001)  # slow era
        clock.t += 100.0  # the slow era slides out of the window
        self._complete(rec, clock, "a", queue_s=0.001, device_s=0.001, n=3)
        st = rec.stanza()
        # windowed p99 reflects only the recent fast ticks
        assert st["tenants"]["a"]["p99_ms"] < 100.0
        # exact counters still cover the lifetime of the window epoch
        assert st["tenants"]["a"]["ticks"] == 4

    def test_stride_decimation_bounds_samples(self):
        clock = _Clock()
        rec = RequestRecorder(
            enabled=True, window_s=1e9, sample_cap=16, clock=clock
        )
        self._complete(rec, clock, "a", 0.001, 0.001, n=200)
        stats = rec._tenants["a"]
        assert len(stats.samples) <= 16
        assert stats.stride > 1
        assert stats.ticks == 200  # exact count survives decimation

    def test_overflow_shed_after_reset_releases_its_depth_slot(self):
        """Regression: a tick folded into the overflow bucket at
        enqueue must release THAT bucket's depth slot when shed after
        a reset_window — the trace carries the folded label, so no
        phantom occupancy can survive on the overflow entry."""
        clock = _Clock()
        rec = RequestRecorder(enabled=True, max_tenants=2, clock=clock)
        self._complete(rec, clock, "a", 0.001, 0.001)
        self._complete(rec, clock, "b", 0.001, 0.001)
        tr = rec.enqueue("s3", "t3")  # folds: table is full
        assert tr.tenant == obs_request.OVERFLOW_TENANT
        rec.reset_window()  # carries the live overflow depth slot
        assert rec.queue_depths()[obs_request.OVERFLOW_TENANT] == 1
        rec.shed(tr, "pressure")
        depths = rec.queue_depths()
        assert depths.get(obs_request.OVERFLOW_TENANT, 0) == 0
        assert all(v == 0 for v in depths.values()), depths

    def test_tenant_cardinality_bounded(self):
        clock = _Clock()
        rec = RequestRecorder(enabled=True, max_tenants=4, clock=clock)
        for i in range(10):
            self._complete(rec, clock, f"t{i}", 0.001, 0.001)
        st = rec.stanza()
        names = set(rec._tenants)
        assert len(names) <= 5  # 4 exact + the overflow bucket
        assert obs_request.OVERFLOW_TENANT in names
        assert st["overall"]["ticks"] == 10  # nothing dropped, only folded

    def test_reset_window_carries_live_queue_depth(self):
        """A post-warmup reset taken while ticks are still pending must
        carry their depth slots into the new window — dropping them
        would under-report a backlogged tenant and desync the
        admit-side decrements."""
        clock = _Clock()
        rec = RequestRecorder(enabled=True, clock=clock)
        t1 = rec.enqueue("s1", "a")
        t2 = rec.enqueue("s2", "a")
        rec.reset_window()
        assert rec.queue_depths()["a"] == 2
        assert rec._tenants["a"].max_queue_depth == 2
        rec.admit([t1])
        rec.shed(t2, "pressure")
        assert rec.queue_depths()["a"] == 0
        # counters describe the NEW window only
        assert rec._tenants["a"].ticks == 0

    def test_queue_depth_released_on_admit_and_shed(self):
        clock = _Clock()
        rec = RequestRecorder(enabled=True, clock=clock)
        t1 = rec.enqueue("s1", "a")
        t2 = rec.enqueue("s2", "a")
        assert rec.queue_depths()["a"] == 2
        rec.admit([t1])
        assert rec.queue_depths()["a"] == 1
        rec.shed(t2, "pressure")
        assert rec.queue_depths()["a"] == 0
        assert rec._tenants["a"].sheds == 1

    def test_disabled_is_noop(self):
        rec = RequestRecorder(enabled=False)
        assert rec.enqueue("s", "t") is None
        rec.admit([None])
        rec.shed(None, "x")
        rec.complete_group([None], kernel="k", bucket=8)
        rec.flush_done()
        assert rec.stanza()["overall"]["ticks"] == 0

    def test_stanza_caps_tenant_rows(self):
        clock = _Clock()
        rec = RequestRecorder(enabled=True, max_tenants=64, clock=clock)
        for i in range(8):
            self._complete(rec, clock, f"t{i}", 0.001, 0.001)
        st = rec.stanza(top=3)
        assert len(st["tenants"]) == 3
        assert st["tenants_omitted"] == 5

    def test_flush_plan_attribution(self):
        """`note_flush_plan` folds the scheduler's per-flush DRR
        decisions into the stanza: served/stranded accumulate across
        flushes, share/credit are last-seen, credit_max is the window
        peak — so a spread regression is attributable to the ORDER the
        scheduler chose, not just the traffic."""
        clock = _Clock()
        rec = RequestRecorder(enabled=True, clock=clock)
        assert rec.stanza()["scheduler"] is None  # nothing recorded yet
        rec.note_flush_plan(
            "drr",
            [
                {"tenant": "hot", "share": 3.0, "served": 6,
                 "stranded": 2, "credit": 2.0},
                {"tenant": "quiet", "share": 1.0, "served": 2,
                 "stranded": 0, "credit": 0.0},
            ],
            credit_cap=4.0,
        )
        rec.note_flush_plan(
            "drr",
            [
                {"tenant": "hot", "share": 3.0, "served": 5,
                 "stranded": 0, "credit": 1.0},
            ],
            credit_cap=4.0,
        )
        plan = rec.stanza()["scheduler"]
        assert plan["order"] == "drr" and plan["credit_cap"] == 4.0
        assert plan["last_flush_order"] == ["hot"]
        hot, quiet = plan["tenants"]["hot"], plan["tenants"]["quiet"]
        assert (hot["served"], hot["stranded"]) == (11, 2)  # accumulated
        assert (hot["credit"], hot["credit_max"]) == (1.0, 2.0)
        assert (quiet["served"], quiet["share"]) == (2, 1.0)
        rec.reset_window()
        assert rec.stanza()["scheduler"] is None  # window semantics

    def test_flush_plan_tenant_rows_bounded(self):
        clock = _Clock()
        rec = RequestRecorder(enabled=True, max_tenants=2, clock=clock)
        rec.note_flush_plan(
            "drr",
            [{"tenant": f"t{i}", "share": 1.0, "served": 1,
              "stranded": 0, "credit": 0.0} for i in range(8)],
        )
        plan = rec.stanza()["scheduler"]
        assert len(plan["tenants"]) <= 3  # 2 exact + the overflow fold
        assert obs_request.OVERFLOW_TENANT in plan["tenants"]
        total = sum(r["served"] for r in plan["tenants"].values())
        assert total == 8  # folded, never dropped


class TestFlightHarvest:
    def test_interleaved_flights_stamp_their_own_traces(self):
        """PR 18 regression: two overlapped in-flight flushes — each
        ``note_harvest`` stamps ONLY its own flight's traces, from the
        harvest site. (The naive wiring stamped harvest from the
        dispatch site, so overlapping flights shared one clock read
        and the hidden/stall split collapsed to zero.)"""
        clock = _Clock()
        rec = RequestRecorder(enabled=True, clock=clock)

        def dispatched(tenant):
            tr = rec.enqueue("s-" + tenant, tenant)
            clock.t += 0.1
            rec.admit([tr])
            rec.stage([tr], "bucket")
            clock.t += 0.01
            rec.stage([tr], "dispatch")
            return tr

        tr1 = dispatched("a")          # dispatch at t=0.11
        rec.begin_flight(1, [tr1])
        clock.t += 0.05                # flight 1 airborne while 2 forms
        tr2 = dispatched("b")          # dispatch at t=0.27
        rec.begin_flight(2, [tr2])
        assert rec.in_flight_depth() == 2
        clock.t += 0.2
        rec.note_harvest(1)            # t=0.47
        clock.t += 0.3
        rec.note_harvest(2)            # t=0.77
        assert rec.in_flight_depth() == 0
        assert tr1.t_harvest == pytest.approx(0.47)
        assert tr2.t_harvest == pytest.approx(0.77)
        clock.t += 0.1                 # both sync-complete at t=0.87
        rec.stage([tr1, tr2], "device")
        clock.t += 0.001
        rec.complete_group([tr1, tr2], kernel="update", bucket=8)
        d1, d2 = tr1.decompose(), tr2.decompose()
        # hidden = dispatch->harvest (latency the pipeline hid behind
        # host work); stall = harvest->device (residual true wait)
        assert d1["hidden_s"] == pytest.approx(0.36)
        assert d1["stall_s"] == pytest.approx(0.40)
        assert d2["hidden_s"] == pytest.approx(0.50)
        assert d2["stall_s"] == pytest.approx(0.10)
        rec.flush_done()
        st = rec.stanza()
        assert st["pipeline"]["in_flight_depth"] == 0
        assert st["pipeline"]["in_flight_peak"] == 2
        assert st["pipeline"]["harvested_flights"] == 2
        assert st["overall"]["overlap_share"] == pytest.approx(
            (0.36 + 0.50) / (0.76 + 0.60), abs=1e-4
        )

    def test_unknown_flight_harvest_is_noop(self):
        clock = _Clock()
        rec = RequestRecorder(enabled=True, clock=clock)
        rec.note_harvest(999)  # never registered: must not raise
        assert rec.in_flight_depth() == 0

    def test_reset_window_carries_live_flights(self):
        """Live flights survive a window reset exactly like queue
        occupancy: the peak restarts at the carried depth."""
        clock = _Clock()
        rec = RequestRecorder(enabled=True, clock=clock)
        tr = rec.enqueue("s", "a")
        rec.begin_flight(7, [tr])
        rec.reset_window()
        assert rec.in_flight_depth() == 1
        st = rec.stanza()
        assert st["pipeline"]["in_flight_peak"] == 1
        assert st["pipeline"]["harvested_flights"] == 0
        rec.note_harvest(7)
        assert tr.t_harvest is not None


class TestSchedulerIntegration:
    def _sched(self, **kw):
        model = MultinomialHMM(K=2, L=3)
        snap = _fake_snapshot(model)
        rec = RequestRecorder(enabled=True, window_s=600.0)
        sched = MicroBatchScheduler(
            model, buckets=(4,), recorder=rec, **kw
        )
        return model, snap, sched, rec

    def test_default_tenant_is_series(self):
        _, snap, sched, rec = self._sched()
        sched.attach_many([("a", snap, None), ("b", snap, None)])
        sched.submit("a", {"x": 1})
        sched.submit("b", {"x": 2})
        sched.flush()
        assert set(rec.stanza()["tenants"]) == {"a", "b"}

    def test_attach_tenant_binds_and_submit_overrides(self):
        _, snap, sched, rec = self._sched()
        sched.attach("a", snap, tenant="alpha")
        sched.attach("b", snap)
        sched.submit("a", {"x": 1})  # attach-time tenant
        sched.submit("b", {"x": 1}, tenant="beta")  # per-submit override
        sched.flush()
        assert set(rec.stanza()["tenants"]) == {"alpha", "beta"}

    def test_per_tenant_quota_sheds_offender_only(self):
        """The AdmissionPolicy satellite: the quota keys on tenant, and
        the pressure shed stays inside the offending tenant."""
        _, snap, sched, rec = self._sched(
            admission=AdmissionPolicy(max_pending_per_series=2)
        )
        sched.attach_many(
            [(f"h{i}", snap, None, "hot") for i in range(4)]
            + [("q0", snap, None, "quiet")]
        )
        sched.submit("q0", {"x": 0})
        for i in range(4):  # 4 hot submits against a quota of 2
            sched.submit(f"h{i}", {"x": 0})
        out = sched.flush()
        shed = [r for r in out if r.shed]
        assert len(shed) == 2
        # the quiet tenant's tick survived; the shed ones are hot's
        assert all(r.series_id.startswith("h") for r in shed)
        assert all("tenant='hot'" in r.error for r in shed)
        assert rec.stanza()["tenants"]["hot"]["sheds"] == 2
        assert rec.stanza()["tenants"]["quiet"]["sheds"] == 0

    def test_default_tenant_quota_matches_old_per_series(self):
        """Default tenant = series: the quota behaves bit-for-bit like
        the historical per-series quota (each series its own budget)."""
        _, snap, sched, _ = self._sched(
            admission=AdmissionPolicy(max_pending_per_series=2)
        )
        sched.attach_many([("a", snap, None), ("b", snap, None)])
        for _ in range(3):
            sched.submit("a", {"x": 0})
        sched.submit("b", {"x": 0})
        out = sched.flush()
        shed = [r for r in out if r.shed]
        # series a over-quota sheds ITS oldest; b untouched
        assert len(shed) == 1 and shed[0].series_id == "a"

    def test_shed_counter_gains_tenant_label(self):
        obs_metrics.reset()
        obs_metrics.enable()
        try:
            _, snap, sched, _ = self._sched()
            sched.attach("a", snap, tenant="alpha")
            sched.submit("unknown", {"x": 0}, tenant="ghost")  # sheds
            sched.flush()
            snap_m = obs_metrics.snapshot()
            assert snap_m["serve.shed_ticks{tenant=ghost}"]["value"] == 1
        finally:
            obs_metrics.use_env()
            obs_metrics.reset()

    def test_shed_label_cardinality_bounded(self):
        """Tenant = series at fleet scale must not create one labeled
        instrument per shedding series: past the SHARED bound
        (`obs/request.py` ``DEFAULT_MAX_TENANTS``), sheds fold into
        the overflow label — the recorder's own discipline, one
        constant for both sinks."""
        cap = obs_request.DEFAULT_MAX_TENANTS
        obs_metrics.reset()
        obs_metrics.enable()
        try:
            m = ServeMetrics()
            for i in range(cap + 20):
                m.note_shed_tick(tenant=f"t{i}")
            keys = [
                k
                for k in obs_metrics.snapshot()
                if k.startswith("serve.shed_ticks{")
            ]
            assert len(keys) == cap + 1  # exact + overflow
            over = obs_metrics.snapshot()[
                "serve.shed_ticks{tenant=" + obs_request.OVERFLOW_TENANT + "}"
            ]
            assert over["value"] == 20  # nothing dropped, only folded
            assert m.shed_ticks == cap + 20
        finally:
            obs_metrics.use_env()
            obs_metrics.reset()

    def test_decomposition_and_compile_flat_through_replay(self):
        """The bench acceptance shape in miniature: a sustained replay
        decomposes per tenant AND the compile count stays flat."""
        _, snap, sched, rec = self._sched()
        sched.attach_many(
            [("a", snap, None, "t0"), ("b", snap, None, "t1")]
        )
        for t in range(2):  # warmup: init + update compiles
            sched.submit("a", {"x": t})
            sched.submit("b", {"x": t})
            sched.flush()
        warm = sched.metrics.compile_count
        rec.reset_window()
        for t in range(6):
            sched.submit("a", {"x": t % 3})
            sched.submit("b", {"x": (t + 1) % 3})
            out = sched.flush()
            assert len(out) == 2
        assert sched.metrics.compile_count == warm  # flat
        st = rec.stanza()
        for tenant in ("t0", "t1"):
            row = st["tenants"][tenant]
            assert row["ticks"] == 6
            for k in ("queue_share", "device_share", "other_share"):
                assert isinstance(row[k], float)
            assert row["queue_share"] + row["device_share"] + row[
                "other_share"
            ] == pytest.approx(1.0, abs=0.01)
        assert st["fairness"]["mean_flush_tenants"] == pytest.approx(2.0)

    def test_detach_sheds_pending_with_tenant(self):
        _, snap, sched, rec = self._sched()
        sched.attach("a", snap, tenant="alpha")
        sched.submit("a", {"x": 0})
        sched.detach("a")
        out = sched.flush()
        assert len(out) == 1 and out[0].shed
        assert rec.stanza()["tenants"]["alpha"]["sheds"] == 1
        # tenant pending table released (no leak)
        assert sched._pending_tenant_count == {}


class TestStalenessAcrossDetachAndPageIn:
    """ISSUE 10 satellite: `serve.snapshot_staleness_seconds` across
    detach() -> pager re-attach. The gauge must (a) drop the detached
    series — the oldest-attach watermark moves to the survivors — and
    (b) restart the series' age on page-in instead of resurrecting the
    original attach time."""

    def test_gauge_drops_detached_and_restarts_on_page_in(self, tmp_path):
        model = MultinomialHMM(K=2, L=3)
        reg = SnapshotRegistry(str(tmp_path))
        snap = _fake_snapshot(model)
        reg.save("a", snap)
        reg.save("b", snap)
        pager = SnapshotPager(reg, budget_bytes=1 << 20)
        metrics = ServeMetrics()
        sched = MicroBatchScheduler(
            model, buckets=(4,), registry=reg, metrics=metrics, pager=pager
        )
        sched.attach("a", reg.load("a"))
        time.sleep(0.05)
        t_before_b = obs_request.now()
        sched.attach("b", reg.load("b"))
        sched.submit("a", {"x": 0})
        sched.submit("b", {"x": 0})
        sched.flush()
        # oldest serving posterior is a's: staleness >= a's age > b's
        s_both = metrics.staleness_seconds()
        assert s_both >= 0.05
        # ---- detach a: the watermark must move to b, not keep aging
        # on the departed series
        assert sched.detach("a")
        sched.submit("b", {"x": 1})
        sched.flush()
        s_after_detach = metrics.staleness_seconds()
        assert s_after_detach <= obs_request.now() - t_before_b + 0.01
        # ---- page a back in: its age must RESTART at the re-attach,
        # not resurrect the original attach time
        time.sleep(0.05)
        t_before_pagein = obs_request.now()
        sched.submit("a", {"x": 1})  # transparent page-in
        out = sched.flush()
        assert any(r.series_id == "a" and not r.shed for r in out)
        assert sched._attach_t["a"] >= t_before_pagein
        # the oldest posterior is now b's (attached before a's page-in)
        assert sched._oldest_attach_t == sched._attach_t["b"]
        s_after_pagein = metrics.staleness_seconds()
        assert s_after_pagein <= obs_request.now() - t_before_b + 0.01


class TestTenantSurvivesPaging:
    def test_explicit_tenant_kept_across_evict_and_page_in(self, tmp_path):
        """A pager eviction detaches the series; its explicit tenant
        binding must survive so the transparent page-in re-attaches it
        under the SAME tenant — a hot tenant must not escape its quota
        pool (or its attribution) by having series page out and back
        in."""
        model = MultinomialHMM(K=2, L=3)
        reg = SnapshotRegistry(str(tmp_path))
        snap = _fake_snapshot(model)
        reg.save("a", snap)
        reg.save("b", snap)
        # budget fits ONE snapshot: attaching b evicts a
        pager = SnapshotPager(
            reg, budget_bytes=int(np.asarray(snap.draws).nbytes * 1.5)
        )
        rec = RequestRecorder(enabled=True, window_s=600.0)
        sched = MicroBatchScheduler(
            model, buckets=(4,), registry=reg, pager=pager, recorder=rec
        )
        sched.attach("a", reg.load("a"), tenant="alpha")
        sched.submit("a", {"x": 0})
        sched.flush()
        sched.attach("b", reg.load("b"))  # evicts a (LRU) -> detach
        assert "a" not in sched.series_ids()
        sched.submit("a", {"x": 1})  # transparent page-in, no tenant arg
        out = sched.flush()
        assert any(r.series_id == "a" and not r.shed for r in out)
        # both of a's ticks attributed to its bound tenant, not "a"
        tenants = rec.stanza()["tenants"]
        assert tenants["alpha"]["ticks"] == 2
        assert "a" not in tenants


class TestCheckGuardsInvariant10:
    def _run_on(self, tmp_path):
        return subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "check_guards.py"),
                str(tmp_path),
            ],
            capture_output=True,
            text=True,
        )

    def test_raw_perf_counter_in_serve_flagged(self, tmp_path):
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "rogue.py").write_text(
            "import time\n\ndef f():\n    return time.perf_counter()\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "raw `perf_counter` read in the serve layer" in proc.stdout

    def test_bare_imported_perf_counter_flagged(self, tmp_path):
        # the from-import spelling must trip too, or the check is
        # trivially evaded
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "rogue.py").write_text(
            "from time import perf_counter as pc\n\n"
            "def f():\n    return pc()\n"
        )
        proc = self._run_on(tmp_path)
        assert proc.returncode == 1
        assert "serve layer" in proc.stdout

    def test_request_plane_clock_passes(self, tmp_path):
        serve = tmp_path / "hhmm_tpu" / "serve"
        serve.mkdir(parents=True)
        (serve / "clean.py").write_text(
            "from hhmm_tpu.obs import request as obs_request\n\n"
            "def f():\n    return obs_request.now()\n"
        )
        proc = self._run_on(tmp_path)
        # the toy repo trips OTHER invariants (missing sampler modules);
        # the serve-layer clock confinement itself must be clean
        assert "serve layer" not in proc.stdout, proc.stdout

    def test_perf_counter_outside_serve_unconstrained(self, tmp_path):
        # invariant 10 is scoped: obs/ and apps/ keep their sanctioned
        # perf_counter reads (invariants 5a/9 govern those)
        pkg = tmp_path / "hhmm_tpu" / "obs"
        pkg.mkdir(parents=True)
        (pkg / "timing.py").write_text(
            "import time\n\ndef f():\n    return time.perf_counter()\n"
        )
        proc = self._run_on(tmp_path)
        assert "serve layer" not in proc.stdout, proc.stdout

    def test_repo_passes_invariant_10(self, check_guards_repo):
        proc = check_guards_repo  # one shared repo scan (conftest)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "serve-layer clocks confined" in proc.stdout
