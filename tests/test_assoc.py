"""Time-parallel engine (kernels/semiring.py, kernels/assoc.py,
kernels/dispatch.py) vs the sequential lax.scan kernels and the NumPy
oracles, plus the sequence-sharded filter on a virtual CPU mesh."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hhmm_tpu.core.lmath import MASK_NEG, log_normalize
from hhmm_tpu.kernels import (
    backward_assoc,
    backward_pass,
    ffbs_dispatch,
    ffbs_fused,
    forward_backward,
    forward_filter,
    forward_filter_assoc,
    forward_filter_seqshard,
    smooth_assoc,
    use_assoc,
    viterbi,
    viterbi_assoc,
)
from hhmm_tpu.kernels.assoc import ffbs_assoc, ffbs_assoc_sample
from hhmm_tpu.kernels.dispatch import (
    forward_filter_dispatch,
    viterbi_dispatch,
)
from hhmm_tpu.kernels.ffbs import ffbs_invcdf_reference

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _inputs(rng, T, K, time_varying=False, dtype=jnp.float32):
    log_pi = log_normalize(jnp.asarray(rng.normal(size=(K,)), dtype))
    shape = (T - 1, K, K) if time_varying else (K, K)
    log_A = log_normalize(jnp.asarray(rng.normal(size=shape), dtype), axis=-1)
    log_obs = jnp.asarray(rng.normal(size=(T, K)) - 1.0, dtype)
    return log_pi, log_A, log_obs


def _tol(dtype):
    # acceptance thresholds: assoc must match the sequential kernels to
    # <=1e-5 (f32) / <=1e-10 (f64) — reassociation is the only slack
    return (
        dict(rtol=1e-5, atol=1e-5)
        if dtype == jnp.float32
        else dict(rtol=1e-10, atol=1e-10)
    )


class TestSemiring:
    def test_logsumexp_matmul_associative(self, rng):
        from hhmm_tpu.kernels.semiring import logsumexp_matmul, semiring_eye

        A, B, C = (jnp.asarray(rng.normal(size=(4, 4))) for _ in range(3))
        left = logsumexp_matmul(logsumexp_matmul(A, B), C)
        right = logsumexp_matmul(A, logsumexp_matmul(B, C))
        np.testing.assert_allclose(left, right, rtol=1e-6, atol=1e-6)
        eye = semiring_eye(4, A.dtype)
        np.testing.assert_allclose(logsumexp_matmul(eye, A), A, rtol=1e-6)
        np.testing.assert_allclose(logsumexp_matmul(A, eye), A, rtol=1e-6)

    def test_maxplus_matmul_associative(self, rng):
        from hhmm_tpu.kernels.semiring import maxplus_matmul, semiring_eye

        A, B, C = (jnp.asarray(rng.normal(size=(3, 3))) for _ in range(3))
        left = maxplus_matmul(maxplus_matmul(A, B), C)
        right = maxplus_matmul(A, maxplus_matmul(B, C))
        np.testing.assert_allclose(left, right, rtol=1e-6, atol=1e-6)
        eye = semiring_eye(3, A.dtype)
        np.testing.assert_allclose(maxplus_matmul(A, eye), A, rtol=1e-6)

    def test_compose_maps(self, rng):
        from hhmm_tpu.kernels.semiring import compose_maps, identity_map

        K = 5
        f = jnp.asarray(rng.integers(0, K, size=(K,)), jnp.int32)
        g = jnp.asarray(rng.integers(0, K, size=(K,)), jnp.int32)
        h = jnp.asarray(rng.integers(0, K, size=(K,)), jnp.int32)
        fg = compose_maps(f, g)
        assert all(int(fg[j]) == int(f[int(g[j])]) for j in range(K))
        left = compose_maps(compose_maps(f, g), h)
        right = compose_maps(f, compose_maps(g, h))
        assert (np.asarray(left) == np.asarray(right)).all()
        ident = identity_map(K)
        assert (np.asarray(compose_maps(f, ident)) == np.asarray(f)).all()
        assert (np.asarray(compose_maps(ident, f)) == np.asarray(f)).all()

    def test_combine_all_masked_grads_finite(self, rng):
        """The risk spot of the issue: an all-(−inf) fiber in a combine
        (identity elements meeting impossible evidence) must have
        finite (zero) cotangents, not NaN."""
        from hhmm_tpu.kernels.semiring import logsumexp_matmul

        A = jnp.asarray(rng.normal(size=(3, 3)))
        B = jnp.full((3, 3), -jnp.inf)

        def f(a):
            out = logsumexp_matmul(a, B)
            return jnp.sum(jnp.where(jnp.isfinite(out), out, 0.0))

        g = jax.grad(f)(A)
        assert np.isfinite(np.asarray(g)).all()


class TestAssoc:
    @pytest.mark.parametrize("time_varying", [False, True])
    @pytest.mark.parametrize("T", [1, 2, 7, 64])
    def test_matches_sequential(self, rng, T, time_varying):
        if T == 1 and time_varying:
            pytest.skip("no transitions")
        log_pi, log_A, log_obs = _inputs(rng, T, 3, time_varying)
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs)
        a, ll = forward_filter_assoc(log_pi, log_A, log_obs)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)

    @pytest.mark.parametrize("time_varying", [False, True])
    def test_T1_edge_case(self, rng, time_varying):
        """T=1 must early-return BEFORE the T-1 slice validation — the
        reordered guard of the issue (a time-varying caller has zero
        transition slices at T=1)."""
        log_pi, _, log_obs = _inputs(rng, 1, 3)
        log_A = (
            jnp.zeros((0, 3, 3))
            if time_varying
            else log_normalize(jnp.asarray(rng.normal(size=(3, 3))), axis=-1)
        )
        a, ll = forward_filter_assoc(log_pi, log_A, log_obs)
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs) if not time_varying else (a, ll)
        assert a.shape == (1, 3)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=1e-6)
        np.testing.assert_allclose(
            float(ll), float(jnp.asarray(jax.scipy.special.logsumexp(a[0]))), rtol=1e-6
        )

    def test_rejects_wrong_slice_count(self, rng):
        log_pi, _, log_obs = _inputs(rng, 8, 3)
        bad = jnp.zeros((3, 3, 3))  # needs T-1 = 7 slices
        with pytest.raises(ValueError, match="T-1"):
            forward_filter_assoc(log_pi, bad, log_obs)

    def test_masked_matches_sequential(self, rng):
        T, K = 24, 4
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        mask = jnp.asarray((np.arange(T) < 17).astype(np.float32))
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs, mask)
        a, ll = forward_filter_assoc(log_pi, log_A, log_obs, mask)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)

    def test_gated_entries(self, rng):
        """MASK_NEG-gated transitions (Tayal hard gating) agree."""
        T, K = 40, 4
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        log_A = log_A.at[0, 3].set(MASK_NEG).at[2, 1].set(MASK_NEG)
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs)
        a, ll = forward_filter_assoc(log_pi, log_A, log_obs)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)

    def test_impossible_evidence_degrades(self, rng):
        """An all-(−inf) observation row must degrade like
        safe_log_normalize — −inf filter values, zero NaNs — in BOTH
        kernels, and they must agree."""
        T, K = 24, 3
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        log_obs = log_obs.at[9].set(-jnp.inf)
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs)
        a, ll = forward_filter_assoc(log_pi, log_A, log_obs)
        assert not np.isnan(np.asarray(a)).any() and not np.isnan(float(ll))
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        assert float(ll) == float(ll_ref) == -np.inf

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_impossible_state_grads_finite(self, rng):
        """An all-(−inf) COLUMN (state impossible at every step) makes
        the prefix products carry fully-(−inf) columns; the guarded
        vecmat must keep gradients finite and equal to the sequential
        filter's (the raw log_vecmat VJP is NaN there — the check_guards
        wrapper-import ban pins the fix)."""
        T, K = 14, 3
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        lo_bad = log_obs.at[:, 1].set(-jnp.inf)
        g = jax.grad(
            lambda p, A: forward_filter_assoc(p, A, lo_bad)[1], argnums=(0, 1)
        )(log_pi, log_A)
        g_ref = jax.grad(
            lambda p, A: forward_filter(p, A, lo_bad)[1], argnums=(0, 1)
        )(log_pi, log_A)
        for a, b in zip(g, g_ref):
            assert np.isfinite(np.asarray(a)).all()
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
            )

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); test_f64 + the f64 oracle arms keep x64 parity in tier-1
    def test_f64_tight_tolerance(self, rng):
        with jax.experimental.enable_x64():
            log_pi, log_A, log_obs = _inputs(rng, 24, 4, dtype=jnp.float64)
            a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs)
            a, ll = forward_filter_assoc(log_pi, log_A, log_obs)
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(a_ref), **_tol(jnp.float64)
            )
            np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-12)

    def test_oracle(self, rng):
        from tests.oracle import forward_np, random_hmm

        log_pi, log_A, log_obs = random_hmm(np.random.default_rng(5), 3, 17)
        a_np, ll_np = forward_np(log_pi, log_A, log_obs)
        a, ll = forward_filter_assoc(
            jnp.asarray(log_pi, jnp.float32),
            jnp.asarray(log_A, jnp.float32),
            jnp.asarray(log_obs, jnp.float32),
        )
        np.testing.assert_allclose(np.asarray(a), a_np, rtol=2e-5, atol=1e-4)
        np.testing.assert_allclose(float(ll), ll_np, rtol=1e-5)

    def test_grad_matches_sequential(self, rng):
        log_pi, log_A, log_obs = _inputs(rng, 24, 3)

        def ll_assoc(*a):
            return forward_filter_assoc(*a)[1]

        def ll_seq(*a):
            return forward_filter(*a)[1]

        g = jax.grad(ll_assoc, argnums=(0, 1, 2))(log_pi, log_A, log_obs)
        g_ref = jax.grad(ll_seq, argnums=(0, 1, 2))(log_pi, log_A, log_obs)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)

    def test_vmap(self, rng):
        B, T, K = 4, 12, 3
        packs = [_inputs(np.random.default_rng(i), T, K) for i in range(B)]
        lp, lA, lo = (jnp.stack([p[i] for p in packs]) for i in range(3))
        a, ll = jax.vmap(forward_filter_assoc)(lp, lA, lo)
        a_ref, ll_ref = jax.vmap(forward_filter)(lp, lA, lo)
        np.testing.assert_allclose(np.asarray(ll), np.asarray(ll_ref), rtol=1e-5)


class TestBackwardSmooth:
    @pytest.mark.parametrize("time_varying", [False, True])
    @pytest.mark.parametrize("T", [1, 2, 9, 28])
    def test_backward_matches_sequential(self, rng, T, time_varying):
        if T == 1 and time_varying:
            pytest.skip("no transitions")
        _, log_A, log_obs = _inputs(rng, T, 3, time_varying)
        b_ref = backward_pass(log_A, log_obs)
        b = backward_assoc(log_A, log_obs)
        np.testing.assert_allclose(np.asarray(b), np.asarray(b_ref), rtol=2e-5, atol=1e-5)

    def test_backward_masked(self, rng):
        T, K = 24, 4
        _, log_A, log_obs = _inputs(rng, T, K)
        mask = jnp.asarray((np.arange(T) < 17).astype(np.float32))
        b_ref = backward_pass(log_A, log_obs, mask)
        b = backward_assoc(log_A, log_obs, mask)
        np.testing.assert_allclose(np.asarray(b), np.asarray(b_ref), rtol=2e-5, atol=1e-5)

    def test_backward_oracle_f64(self):
        from tests.oracle import backward_np, random_hmm

        with jax.experimental.enable_x64():
            log_pi, log_A, log_obs = random_hmm(np.random.default_rng(3), 4, 21)
            b_np = backward_np(log_A, log_obs)
            b = backward_assoc(jnp.asarray(log_A), jnp.asarray(log_obs))
            np.testing.assert_allclose(np.asarray(b), b_np, **_tol(jnp.float64))

    def test_backward_impossible_evidence(self, rng):
        T, K = 20, 3
        _, log_A, log_obs = _inputs(rng, T, K)
        log_obs = log_obs.at[7].set(-jnp.inf)
        b_ref = backward_pass(log_A, log_obs)
        b = backward_assoc(log_A, log_obs)
        assert not np.isnan(np.asarray(b)).any()
        np.testing.assert_allclose(np.asarray(b), np.asarray(b_ref), rtol=2e-5, atol=1e-5)

    @pytest.mark.parametrize("time_varying", [False, True])
    def test_smooth_matches_forward_backward(self, rng, time_varying):
        T, K = 24, 3
        log_pi, log_A, log_obs = _inputs(rng, T, K, time_varying)
        mask = jnp.asarray((np.arange(T) < 19).astype(np.float32))
        ref = forward_backward(log_pi, log_A, log_obs, mask)
        out = smooth_assoc(log_pi, log_A, log_obs, mask)
        for r, o in zip(ref, out):
            np.testing.assert_allclose(np.asarray(o), np.asarray(r), rtol=2e-5, atol=1e-5)

    def test_smooth_oracle_brute(self):
        """Exact smoothing marginals by K^T path enumeration (tiny T)."""
        from tests.oracle import smoothing_marginals_brute, random_hmm

        with jax.experimental.enable_x64():
            log_pi, log_A, log_obs = random_hmm(np.random.default_rng(9), 3, 6)
            gamma_np = smoothing_marginals_brute(log_pi, log_A, log_obs)
            _, _, log_gamma, _ = smooth_assoc(
                jnp.asarray(log_pi), jnp.asarray(log_A), jnp.asarray(log_obs)
            )
            np.testing.assert_allclose(
                np.asarray(log_gamma), gamma_np, rtol=1e-8, atol=1e-8
            )


class TestViterbiAssoc:
    @pytest.mark.parametrize("time_varying", [False, True])
    @pytest.mark.parametrize("T", [1, 2, 9, 40])
    def test_matches_sequential(self, rng, T, time_varying):
        if T == 1 and time_varying:
            pytest.skip("no transitions")
        log_pi, log_A, log_obs = _inputs(rng, T, 3, time_varying)
        p_ref, v_ref = viterbi(log_pi, log_A, log_obs)
        p, v = viterbi_assoc(log_pi, log_A, log_obs)
        assert (np.asarray(p) == np.asarray(p_ref)).all()
        np.testing.assert_allclose(float(v), float(v_ref), rtol=1e-6)

    def test_masked(self, rng):
        T, K = 32, 4
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        mask = jnp.asarray((np.arange(T) < 21).astype(np.float32))
        p_ref, v_ref = viterbi(log_pi, log_A, log_obs, mask)
        p, v = viterbi_assoc(log_pi, log_A, log_obs, mask)
        assert (np.asarray(p) == np.asarray(p_ref)).all()
        np.testing.assert_allclose(float(v), float(v_ref), rtol=1e-6)

    def test_gated_entries(self, rng):
        T, K = 40, 4
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        log_A = log_A.at[0, 3].set(MASK_NEG).at[2, 1].set(MASK_NEG)
        p_ref, v_ref = viterbi(log_pi, log_A, log_obs)
        p, v = viterbi_assoc(log_pi, log_A, log_obs)
        assert (np.asarray(p) == np.asarray(p_ref)).all()

    def test_oracle_f64(self):
        from tests.oracle import viterbi_np, random_hmm

        with jax.experimental.enable_x64():
            log_pi, log_A, log_obs = random_hmm(np.random.default_rng(11), 4, 30)
            p_np, v_np = viterbi_np(log_pi, log_A, log_obs)
            p, v = viterbi_assoc(
                jnp.asarray(log_pi), jnp.asarray(log_A), jnp.asarray(log_obs)
            )
            assert (np.asarray(p) == p_np).all()
            np.testing.assert_allclose(float(v), v_np, rtol=1e-12)

    def test_vmap(self, rng):
        B, T, K = 3, 14, 3
        packs = [_inputs(np.random.default_rng(100 + i), T, K) for i in range(B)]
        lp, lA, lo = (jnp.stack([p[i] for p in packs]) for i in range(3))
        p, v = jax.vmap(viterbi_assoc)(lp, lA, lo)
        p_ref, v_ref = jax.vmap(viterbi)(lp, lA, lo)
        assert (np.asarray(p) == np.asarray(p_ref)).all()
        np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref), rtol=1e-6)


class TestFFBSAssoc:
    # jitted class-level comparators: the seeds share one compiled
    # graph per call signature instead of re-tracing the unjitted scans
    # (jit caches the gated arity separately under the same wrapper)
    _ref = staticmethod(jax.jit(ffbs_invcdf_reference))
    _assoc = staticmethod(jax.jit(ffbs_assoc))

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_draw_for_draw_vs_reference(self, seed):
        """Same pre-drawn uniforms → same path as the sequential
        inverse-CDF reference, draw for draw."""
        rng = np.random.default_rng(seed)
        T, K = 37, 4
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        mask = jnp.asarray((np.arange(T) < 25 + seed).astype(np.float32))
        u = jnp.asarray(rng.uniform(size=(T,)).astype(np.float32))
        z_ref, ll_ref = self._ref(log_pi, log_A, log_obs, mask, u)
        z, ll = self._assoc(log_pi, log_A, log_obs, mask, u)
        assert (np.asarray(z) == np.asarray(z_ref)).all()
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-5)

    @pytest.mark.parametrize("seed", [3, 4])
    def test_gated_draw_for_draw(self, seed):
        """Gate-key semantics (`kernels/vg.py`): inconsistent successors
        fall back to the filter draw — identical to the reference."""
        rng = np.random.default_rng(seed)
        T, K = 29, 4
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        mask = jnp.ones((T,), jnp.float32)
        u = jnp.asarray(rng.uniform(size=(T,)).astype(np.float32))
        gate = jnp.asarray(rng.integers(0, 2, size=(T,)).astype(np.float32))
        skey = jnp.asarray((np.arange(K) % 2).astype(np.float32))
        z_ref, ll_ref = self._ref(log_pi, log_A, log_obs, mask, u, gate, skey)
        z, ll = self._assoc(log_pi, log_A, log_obs, mask, u, gate, skey)
        assert (np.asarray(z) == np.asarray(z_ref)).all()
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-5)

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_key_parity_with_ffbs_fused(self, rng):
        """Same PRNG key → same uniforms → same draws as ffbs_fused, so
        the dispatch layer swaps them freely."""
        T, K = 33, 4
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        mask = jnp.asarray((np.arange(T) < 28).astype(np.float32))
        k = jax.random.PRNGKey(7)
        z_f, ll_f = ffbs_fused(k, log_pi, log_A, log_obs, mask)
        z_a, ll_a = ffbs_assoc_sample(k, log_pi, log_A, log_obs, mask)
        assert (np.asarray(z_f) == np.asarray(z_a)).all()
        np.testing.assert_allclose(float(ll_f), float(ll_a), rtol=1e-5)

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_f64(self):
        rng = np.random.default_rng(6)
        with jax.experimental.enable_x64():
            T, K = 21, 3
            log_pi, log_A, log_obs = _inputs(rng, T, K, dtype=jnp.float64)
            mask = jnp.ones((T,), jnp.float64)
            u = jnp.asarray(rng.uniform(size=(T,)))
            z_ref, ll_ref = ffbs_invcdf_reference(log_pi, log_A, log_obs, mask, u)
            z, ll = ffbs_assoc(log_pi, log_A, log_obs, mask, u)
            assert (np.asarray(z) == np.asarray(z_ref)).all()
            np.testing.assert_allclose(float(ll), float(ll_ref), **_tol(jnp.float64))

    def test_T1_and_time_varying_rejected(self, rng):
        log_pi, log_A, log_obs = _inputs(rng, 1, 3)
        u = jnp.asarray(rng.uniform(size=(1,)).astype(np.float32))
        z, ll = ffbs_assoc(log_pi, log_A, log_obs, jnp.ones((1,)), u)
        assert z.shape == (1,)
        with pytest.raises(ValueError, match="homogeneous"):
            ffbs_assoc(
                log_pi, jnp.zeros((7, 3, 3)), jnp.zeros((8, 3)),
                jnp.ones((8,)), jnp.zeros((8,)),
            )

    def test_vmap(self, rng):
        B, T, K = 3, 18, 3
        packs = [_inputs(np.random.default_rng(40 + i), T, K) for i in range(B)]
        lp, lA, lo = (jnp.stack([p[i] for p in packs]) for i in range(3))
        mask = jnp.ones((B, T), jnp.float32)
        u = jnp.asarray(rng.uniform(size=(B, T)).astype(np.float32))
        z, ll = jax.jit(jax.vmap(ffbs_assoc))(lp, lA, lo, mask, u)
        z_ref, ll_ref = jax.jit(jax.vmap(ffbs_invcdf_reference))(lp, lA, lo, mask, u)
        assert (np.asarray(z) == np.asarray(z_ref)).all()
        np.testing.assert_allclose(np.asarray(ll), np.asarray(ll_ref), rtol=1e-5)


class TestDispatch:
    def test_use_assoc_table(self):
        # explicit overrides pass through
        assert use_assoc(4, 8, True) is True
        assert use_assoc(4, 1 << 20, False) is False
        with pytest.raises(ValueError):
            use_assoc(4, 64, "sometimes")
        # table semantics: monotone in T, off above the largest K row,
        # empty table (the measured CPU row) = scan everywhere
        from hhmm_tpu.kernels.dispatch import ASSOC_CROSSOVER

        for platform in ("cpu", "tpu", "default"):
            assert not use_assoc(64, 1 << 20, "auto", platform=platform)
            assert not use_assoc(4, 2, "auto", platform=platform)
            table = ASSOC_CROSSOVER[platform]
            if table:
                k_max, t_min = table[0]
                assert use_assoc(k_max, t_min, "auto", platform=platform)
            else:
                assert not use_assoc(2, 1 << 20, "auto", platform=platform)

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_dispatch_branches_agree(self, rng):
        T, K = 30, 3
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        mask = jnp.asarray((np.arange(T) < 22).astype(np.float32))
        for tp in (True, False):
            a, ll = forward_filter_dispatch(
                log_pi, log_A, log_obs, mask, time_parallel=tp
            )
            a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs, mask)
            np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
            p, v = viterbi_dispatch(log_pi, log_A, log_obs, mask, time_parallel=tp)
            p_ref, _ = viterbi(log_pi, log_A, log_obs, mask)
            assert (np.asarray(p) == np.asarray(p_ref)).all()
            z, _ = ffbs_dispatch(
                jax.random.PRNGKey(0), log_pi, log_A, log_obs, mask,
                time_parallel=tp,
            )
            z_ref, _ = ffbs_fused(jax.random.PRNGKey(0), log_pi, log_A, log_obs, mask)
            assert (np.asarray(z) == np.asarray(z_ref)).all()

    def test_model_generated_routes(self, rng):
        """BaseHMMModel.generated(time_parallel=...) — both branches
        produce the same decode."""
        from hhmm_tpu.models.multinomial_hmm import MultinomialHMM

        m = MultinomialHMM(K=2, L=3)
        x = jnp.asarray(rng.integers(0, 3, size=16))
        theta = m.init_unconstrained(jax.random.PRNGKey(0), {"x": x})
        g_seq = jax.jit(
            lambda t: m.generated(t, {"x": x}, time_parallel=False)
        )(theta[None])
        g_tp = jax.jit(
            lambda t: m.generated(t, {"x": x}, time_parallel=True)
        )(theta[None])
        np.testing.assert_allclose(
            np.asarray(g_tp["gamma"]), np.asarray(g_seq["gamma"]), rtol=2e-5, atol=1e-5
        )
        assert (np.asarray(g_tp["zstar"]) == np.asarray(g_seq["zstar"])).all()

    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_gibbs_time_parallel_parity(self, rng):
        """sample_gibbs draws are identical under forced assoc routing
        (same uniforms, same inverse-CDF math)."""
        from hhmm_tpu.infer import GibbsConfig, sample_gibbs
        from hhmm_tpu.models.multinomial_hmm import MultinomialHMM

        m = MultinomialHMM(K=2, L=3)
        x = jnp.asarray(rng.integers(0, 3, size=24))
        cfg = dict(num_warmup=3, num_samples=4)
        qs_a, _ = sample_gibbs(
            m, {"x": x}, jax.random.PRNGKey(1),
            GibbsConfig(**cfg, time_parallel=True),
        )
        qs_b, _ = sample_gibbs(
            m, {"x": x}, jax.random.PRNGKey(1),
            GibbsConfig(**cfg, time_parallel=False),
        )
        np.testing.assert_allclose(np.asarray(qs_a), np.asarray(qs_b), rtol=1e-5, atol=1e-6)


@pytest.fixture(scope="module")
def sp_mesh():
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs >=4 virtual devices")
    return Mesh(np.asarray(devs[:4]), ("sp",))


@pytest.fixture(scope="module")
def seqshard_jit(sp_mesh):
    """ONE compiled seqshard graph shared by the whole class (eager
    shard_map re-lowers the collective program per call — the dominant
    cost of these tests on the virtual device mesh)."""
    return jax.jit(
        lambda lp, lA, lo, m: forward_filter_seqshard(
            lp, lA, lo, m, mesh=sp_mesh
        )
    )


class TestSeqShard:
    T, K = 32, 3

    def test_matches_sequential_and_jits(self, rng, seqshard_jit):
        log_pi, log_A, log_obs = _inputs(rng, self.T, self.K)
        mask = jnp.ones((self.T,), jnp.float32)
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs)
        a, ll = seqshard_jit(log_pi, log_A, log_obs, mask)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)

    def test_masked(self, rng, seqshard_jit):
        """Tail padding crossing chunk boundaries (same compiled graph)."""
        log_pi, log_A, log_obs = _inputs(rng, self.T, self.K)
        mask = jnp.asarray((np.arange(self.T) < 19).astype(np.float32))
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs, mask)
        a, ll = seqshard_jit(log_pi, log_A, log_obs, mask)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)

    def test_rejects_bad_shapes(self, rng, sp_mesh):
        log_pi, log_A, log_obs = _inputs(rng, 30, 3)
        with pytest.raises(ValueError):
            forward_filter_seqshard(log_pi, log_A, log_obs, mesh=sp_mesh)  # 30 % 4 != 0
        log_pi, lA_t, log_obs = _inputs(rng, 32, 3, time_varying=True)
        with pytest.raises(ValueError):
            forward_filter_seqshard(log_pi, lA_t, log_obs, mesh=sp_mesh)

    def test_compat_shims_execute_body(self, rng, sp_mesh):
        """The version-compat layer (`core/compat.py`): shard_map and
        pcast_varying must actually EXECUTE `_seqshard_body` on this
        JAX — the issue's 3 failures were an AttributeError on
        `jax.shard_map` before the body ever ran, with `lax.pcast`
        untested behind it."""
        from functools import partial

        from jax.sharding import PartitionSpec as P

        from hhmm_tpu.core.compat import pcast_varying, shard_map
        from hhmm_tpu.kernels.assoc import _seqshard_body

        T, K = 16, 2
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        mask = jnp.ones((T,), jnp.float32)
        fn = jax.jit(
            shard_map(
                partial(_seqshard_body, "sp", 4),
                mesh=sp_mesh,
                in_specs=(P(), P(), P("sp", None), P("sp")),
                out_specs=(P("sp", None), P()),
            )
        )
        a, ll = fn(log_pi, log_A, log_obs, mask)
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)
        # the pcast shim's fallback path is the identity (outside any
        # mesh context); the real pcast/pvary is only legal inside a
        # mapped body, where the graph above already executed it
        from jax import lax as _lax

        if not hasattr(_lax, "pcast") and not hasattr(_lax, "pvary"):
            x = jnp.arange(3.0)
            np.testing.assert_array_equal(
                np.asarray(pcast_varying(x, "sp")), np.asarray(x)
            )

    def test_batched_composes_with_series_axis(self, rng):
        """Sequence sharding composes with the batch mesh axis: a 2-D
        (series × sp) mesh, batch sharded over series, time over sp."""
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < 4:
            pytest.skip("needs 4 virtual devices")
        mesh2 = Mesh(np.asarray(devs[:4]).reshape(2, 2), ("series", "sp"))
        B, T, K = 2, 16, 2
        packs = [_inputs(np.random.default_rng(70 + i), T, K) for i in range(B)]
        lp, lA, lo = (jnp.stack([p[i] for p in packs]) for i in range(3))
        mask = jnp.asarray(
            (np.arange(T)[None, :] < np.array([16, 9])[:, None]).astype(
                np.float32
            )
        )
        a, ll = jax.jit(
            lambda *args: forward_filter_seqshard(
                *args, mesh=mesh2, batch_axis_name="series"
            )
        )(lp, lA, lo, mask)
        a_ref, ll_ref = jax.vmap(forward_filter)(lp, lA, lo, mask)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(ll), np.asarray(ll_ref), rtol=1e-6)


class TestAssocSweepBench:
    @pytest.mark.slow  # measured multi-second on the single-core tier-1 host (.tier1_durations.json); full-suite coverage only
    def test_quick_sweep_record(self):
        """`bench.py --assoc-sweep --quick` must exit 0 and emit the
        tayal_assoc_decode_throughput record (the tier-1 regression
        gate on the dispatch crossover)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--assoc-sweep", "--quick", "--cpu"],
            capture_output=True,
            text=True,
            timeout=600,
            cwd=REPO,
            env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        rec = json.loads(out.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "tayal_assoc_decode_throughput"
        assert rec["unit"] == "series/sec"
        assert len(rec["points"]) == 2
        for p in rec["points"]:
            assert p["seq_series_per_sec"] > 0
            assert p["assoc_series_per_sec"] > 0
            assert p["dispatch_auto"] in ("seq", "assoc")

    def test_check_guards_passes(self, check_guards_repo):
        """Re-assert the static pass (semiring invariant included)."""
        out = check_guards_repo  # one shared repo scan (conftest)
        assert out.returncode == 0, out.stdout + out.stderr
