"""Associative-scan and sequence-sharded forward filters vs the
sequential lax.scan kernel (kernels/assoc.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hhmm_tpu.core.lmath import MASK_NEG, log_normalize
from hhmm_tpu.kernels import (
    forward_filter,
    forward_filter_assoc,
    forward_filter_seqshard,
)


def _inputs(rng, T, K, time_varying=False):
    log_pi = log_normalize(jnp.asarray(rng.normal(size=(K,))))
    shape = (T - 1, K, K) if time_varying else (K, K)
    log_A = log_normalize(jnp.asarray(rng.normal(size=shape)), axis=-1)
    log_obs = jnp.asarray(rng.normal(size=(T, K)) - 1.0)
    return log_pi, log_A, log_obs


class TestAssoc:
    @pytest.mark.parametrize("time_varying", [False, True])
    @pytest.mark.parametrize("T", [1, 2, 7, 64])
    def test_matches_sequential(self, rng, T, time_varying):
        if T == 1 and time_varying:
            pytest.skip("no transitions")
        log_pi, log_A, log_obs = _inputs(rng, T, 3, time_varying)
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs)
        a, ll = forward_filter_assoc(log_pi, log_A, log_obs)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)

    def test_masked_matches_sequential(self, rng):
        T, K = 33, 4
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        mask = jnp.asarray((np.arange(T) < 21).astype(np.float32))
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs, mask)
        a, ll = forward_filter_assoc(log_pi, log_A, log_obs, mask)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)

    def test_gated_entries(self, rng):
        """MASK_NEG-gated transitions (Tayal hard gating) agree."""
        T, K = 40, 4
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        log_A = log_A.at[0, 3].set(MASK_NEG).at[2, 1].set(MASK_NEG)
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs)
        a, ll = forward_filter_assoc(log_pi, log_A, log_obs)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)

    def test_grad_matches_sequential(self, rng):
        log_pi, log_A, log_obs = _inputs(rng, 24, 3)

        def ll_assoc(*a):
            return forward_filter_assoc(*a)[1]

        def ll_seq(*a):
            return forward_filter(*a)[1]

        g = jax.grad(ll_assoc, argnums=(0, 1, 2))(log_pi, log_A, log_obs)
        g_ref = jax.grad(ll_seq, argnums=(0, 1, 2))(log_pi, log_A, log_obs)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)

    def test_vmap(self, rng):
        B, T, K = 6, 16, 3
        packs = [_inputs(np.random.default_rng(i), T, K) for i in range(B)]
        lp, lA, lo = (jnp.stack([p[i] for p in packs]) for i in range(3))
        a, ll = jax.vmap(forward_filter_assoc)(lp, lA, lo)
        a_ref, ll_ref = jax.vmap(forward_filter)(lp, lA, lo)
        np.testing.assert_allclose(np.asarray(ll), np.asarray(ll_ref), rtol=1e-5)


class TestSeqShard:
    @pytest.fixture
    def mesh(self):
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < 4:
            pytest.skip("needs >=4 virtual devices")
        return Mesh(np.asarray(devs[:4]), ("sp",))

    def test_matches_sequential(self, rng, mesh):
        T, K = 64, 4
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs)
        a, ll = forward_filter_seqshard(log_pi, log_A, log_obs, mesh=mesh)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)

    def test_masked(self, rng, mesh):
        """Tail padding crossing chunk boundaries."""
        T, K = 64, 3
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        mask = jnp.asarray((np.arange(T) < 37).astype(np.float32))
        a_ref, ll_ref = forward_filter(log_pi, log_A, log_obs, mask)
        a, ll = forward_filter_seqshard(log_pi, log_A, log_obs, mask, mesh=mesh)
        np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-6)

    def test_jit_composes(self, rng, mesh):
        T, K = 32, 3
        log_pi, log_A, log_obs = _inputs(rng, T, K)
        fn = jax.jit(
            lambda *a: forward_filter_seqshard(*a, mesh=mesh)[1]
        )
        _, ll_ref = forward_filter(log_pi, log_A, log_obs)
        np.testing.assert_allclose(float(fn(log_pi, log_A, log_obs)), float(ll_ref), rtol=1e-6)

    def test_rejects_bad_shapes(self, rng, mesh):
        log_pi, log_A, log_obs = _inputs(rng, 30, 3)
        with pytest.raises(ValueError):
            forward_filter_seqshard(log_pi, log_A, log_obs, mesh=mesh)  # 30 % 4 != 0
        log_pi, lA_t, log_obs = _inputs(rng, 32, 3, time_varying=True)
        with pytest.raises(ValueError):
            forward_filter_seqshard(log_pi, lA_t, log_obs, mesh=mesh)
