"""Native C++ zig-zag extractor (native/zigzag.cpp) vs the NumPy oracle
(apps/tayal/features.py). Skipped when no compiler is available."""

import numpy as np
import pytest

from hhmm_tpu.apps.tayal.features import extract_features, to_model_inputs
from hhmm_tpu.native import zigzag as nz

pytestmark = pytest.mark.skipif(
    not nz.available(), reason="native zigzag library unavailable"
)

_FIELDS = ("price", "start", "end", "size_av", "f0", "f1", "f2", "feature", "trend")


def _sim(rng, T):
    price = 10 + 0.01 * np.round(
        np.cumsum(rng.choice([-1, 0, 1], T, p=[0.4, 0.2, 0.4])), 2
    )
    size = rng.integers(1, 500, T).astype(float)
    t = np.cumsum(rng.exponential(2.0, T))
    return price, size, t


class TestNativeParity:
    def test_random_series_exact_match(self, rng):
        checked = 0
        for _ in range(25):
            T = int(rng.integers(60, 4000))
            p, s, t = _sim(rng, T)
            try:
                ref = extract_features(p, s, t, engine="numpy")
            except ValueError as e:
                with pytest.raises(ValueError, match=str(e)):
                    nz.extract_features_native(p, s, t)
                continue
            nat = nz.extract_features_native(p, s, t)
            for f in _FIELDS:
                np.testing.assert_array_equal(
                    getattr(ref, f), getattr(nat, f), err_msg=f
                )
            checked += 1
        assert checked >= 10

    def test_alpha_sensitivity(self, rng):
        p, s, t = _sim(rng, 2000)
        for alpha in (0.1, 0.25, 0.6):
            ref = extract_features(p, s, t, alpha=alpha, engine="numpy")
            nat = nz.extract_features_native(p, s, t, alpha=alpha)
            np.testing.assert_array_equal(ref.feature, nat.feature)

    def test_error_codes(self):
        with pytest.raises(ValueError, match="at least 3 ticks"):
            nz.extract_features_native(
                np.array([1.0, 2.0]), np.ones(2), np.arange(2.0)
            )
        flat = np.full(100, 5.0)
        with pytest.raises(ValueError, match="too few direction changes"):
            nz.extract_features_native(flat, np.ones(100), np.arange(100.0))

    def test_auto_engine_dispatches_native(self, rng):
        p, s, t = _sim(rng, 1500)
        auto = extract_features(p, s, t)  # engine="auto"
        ref = extract_features(p, s, t, engine="numpy")
        np.testing.assert_array_equal(auto.feature, ref.feature)
        x, sign = to_model_inputs(auto.feature)
        assert x.min() >= 0 and x.max() <= 8
        assert set(np.unique(sign)) <= {0, 1}


class TestBatch:
    def test_batch_matches_single(self, rng):
        batch = [_sim(rng, int(rng.integers(400, 2500))) for _ in range(16)]
        outs = nz.extract_features_batch(batch, n_threads=4)
        for (p, s, t), o in zip(batch, outs):
            ref = extract_features(p, s, t, engine="numpy")
            for f in _FIELDS:
                np.testing.assert_array_equal(getattr(ref, f), getattr(o, f))

    def test_batch_per_series_errors(self, rng):
        good = _sim(rng, 800)
        bad = (np.full(50, 3.0), np.ones(50), np.arange(50.0))
        outs = nz.extract_features_batch([good, bad, good])
        assert not isinstance(outs[0], Exception)
        assert isinstance(outs[1], ValueError)
        assert not isinstance(outs[2], Exception)

    def test_empty_batch(self):
        assert nz.extract_features_batch([]) == []
