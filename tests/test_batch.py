"""Batch layer tests: ragged padding, digest cache semantics, batched
fitting (chunking, cache hits, mesh sharding, padding-invariance)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow  # multi-minute suites; fast subset: -m 'not slow'

from hhmm_tpu.batch import (
    ResultCache,
    digest_key,
    fit_batched,
    pad_datasets,
    pad_ragged,
)
from hhmm_tpu.infer import SamplerConfig
from hhmm_tpu.models import GaussianHMM
from hhmm_tpu.sim import hmm_sim, obsmodel_gaussian

A_TRUE = np.array([[0.8, 0.2], [0.3, 0.7]])
P1 = np.array([0.6, 0.4])


def _series(key, T):
    _, x = hmm_sim(key, T, A_TRUE, P1, obsmodel_gaussian([-2.0, 2.0], [0.7, 0.7]))
    return np.asarray(x)


class TestPad:
    def test_pad_ragged(self):
        arrs = [np.arange(3.0), np.arange(5.0)]
        out, mask = pad_ragged(arrs)
        assert out.shape == (2, 5)
        np.testing.assert_array_equal(mask, [[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]])
        np.testing.assert_array_equal(out[0, :3], [0, 1, 2])

    def test_pad_datasets(self):
        ds = [
            {"x": np.arange(3.0), "c": np.float64(1.0)},
            {"x": np.arange(4.0), "c": np.float64(2.0)},
        ]
        out = pad_datasets(ds, time_keys=["x"])
        assert out["x"].shape == (2, 4)
        assert out["mask"].shape == (2, 4)
        np.testing.assert_array_equal(out["c"], [1.0, 2.0])

    def test_too_long_raises(self):
        with pytest.raises(ValueError, match="exceeds"):
            pad_ragged([np.arange(5.0)], length=3)


class TestCache:
    def test_digest_sensitivity(self):
        a = {"x": np.arange(4), "cfg": {"n": 3}}
        b = {"x": np.arange(4), "cfg": {"n": 4}}
        assert digest_key(a) == digest_key({"x": np.arange(4), "cfg": {"n": 3}})
        assert digest_key(a) != digest_key(b)
        assert digest_key(a) != digest_key({"x": np.arange(5), "cfg": {"n": 3}})

    def test_roundtrip(self, tmp_path):
        cache = ResultCache(str(tmp_path))
        key = digest_key("k")
        assert cache.get(key) is None
        cache.put(key, {"a": np.arange(3), "b": np.eye(2)})
        hit = cache.get(key)
        np.testing.assert_array_equal(hit["a"], np.arange(3))
        np.testing.assert_array_equal(hit["b"], np.eye(2))

    def test_disabled_cache(self):
        cache = ResultCache(None)
        cache.put("k", {"a": np.arange(2)})
        assert cache.get("k") is None


CFG = SamplerConfig(num_warmup=150, num_samples=100, num_chains=2, max_treedepth=6)


class TestFitBatched:
    def test_chunked_fit_recovers(self, tmp_path):
        """6 series in chunks of 4 (ragged final chunk): posterior means
        of the well-separated Gaussian HMM recover truth per series."""
        B, T = 6, 300
        xs = np.stack([_series(jax.random.PRNGKey(i), T) for i in range(B)])
        model = GaussianHMM(K=2)
        qs, stats = fit_batched(
            model,
            {"x": xs},
            jax.random.PRNGKey(0),
            CFG,
            chunk_size=4,
            cache_dir=str(tmp_path),
        )
        assert qs.shape[:2] == (B, 2)
        assert float(np.asarray(stats["diverging"]).mean()) < 0.05
        draws = model.constrained_draws(qs)
        mu_hat = np.asarray(draws["mu_k"]).mean(axis=(1, 2))  # [B, K]
        np.testing.assert_allclose(mu_hat, np.tile([-2.0, 2.0], (B, 1)), atol=0.4)

    def test_cache_hit_identical(self, tmp_path):
        B, T = 2, 200
        xs = np.stack([_series(jax.random.PRNGKey(i), T) for i in range(B)])
        model = GaussianHMM(K=2)
        args = (model, {"x": xs}, jax.random.PRNGKey(0), CFG)
        qs1, _ = fit_batched(*args, chunk_size=2, cache_dir=str(tmp_path))
        n_files = len(list(tmp_path.glob("*.npz")))
        qs2, _ = fit_batched(*args, chunk_size=2, cache_dir=str(tmp_path))
        # one fit-chunk entry + one init entry, both reused on rerun
        assert n_files == len(list(tmp_path.glob("*.npz"))) == 2
        np.testing.assert_array_equal(np.asarray(qs1), np.asarray(qs2))

    def test_padding_invariance(self):
        """Masked padding is a no-op: the NUTS target agrees pointwise
        with the exact-length target, and the fitted posteriors agree
        statistically. (Bitwise sample equality is NOT expected — the
        padded program compiles to different fusions whose rounding
        differences get amplified by the chaotic trajectory.)"""
        T = 200
        x = _series(jax.random.PRNGKey(3), T)
        model = GaussianHMM(K=2)
        exact = {"x": x[None], "mask": np.ones((1, T), np.float32)}
        padded_x, mask = pad_ragged([x], length=T + 50)
        padded = {"x": padded_x, "mask": mask}

        # deterministic: identical logp at arbitrary test points
        logp_e = model.make_logp({"x": x, "mask": np.ones(T, np.float32)})
        logp_p = model.make_logp({"x": padded_x[0], "mask": mask[0]})
        for seed in range(3):
            theta = 0.3 * jax.random.normal(jax.random.PRNGKey(seed), (model.n_free,))
            np.testing.assert_allclose(
                float(logp_e(theta)), float(logp_p(theta)), rtol=1e-6
            )

        # statistical: posterior means agree
        qs1, _ = fit_batched(model, exact, jax.random.PRNGKey(0), CFG)
        qs2, _ = fit_batched(model, padded, jax.random.PRNGKey(0), CFG)
        mu1 = np.asarray(model.constrained_draws(qs1)["mu_k"]).mean(axis=(0, 1, 2))
        mu2 = np.asarray(model.constrained_draws(qs2)["mu_k"]).mean(axis=(0, 1, 2))
        np.testing.assert_allclose(mu1, mu2, atol=0.1)

    def test_mesh_sharded_fit(self):
        """Chunk laid out over an 8-device 'series' mesh executes and
        matches the unsharded result."""
        from jax.sharding import Mesh

        devices = jax.devices()
        if len(devices) < 8:
            pytest.skip("needs 8 virtual devices")
        B, T = 8, 120
        xs = np.stack([_series(jax.random.PRNGKey(i), T) for i in range(B)])
        model = GaussianHMM(K=2)
        cfg = SamplerConfig(num_warmup=50, num_samples=30, num_chains=1, max_treedepth=5)
        mesh = Mesh(np.asarray(devices[:8]).reshape(8, 1)[:, 0], ("series",))
        qs_sharded, _ = fit_batched(
            model, {"x": xs}, jax.random.PRNGKey(0), cfg, chunk_size=8, mesh=mesh
        )
        qs_plain, _ = fit_batched(
            model, {"x": xs}, jax.random.PRNGKey(0), cfg, chunk_size=8
        )
        # sharded layout compiles differently; compare posteriors
        # statistically, not bitwise
        mu_s = np.asarray(model.constrained_draws(qs_sharded)["mu_k"]).mean(axis=(1, 2))
        mu_p = np.asarray(model.constrained_draws(qs_plain)["mu_k"]).mean(axis=(1, 2))
        np.testing.assert_allclose(mu_s, mu_p, atol=0.25)

    @pytest.mark.parametrize("gate_mode", ["hard", "stan"])
    def test_mesh_sharded_gibbs(self, gate_mode):
        """Conjugate Gibbs — the bench default sampler — over the
        'series' mesh (VERDICT r3 #3): sharded draws must equal the
        single-device draws (per-series computation is independent and
        keyed identically; only the device layout differs). Covers both
        the homogeneous-kernel path (hard gate) and the time-varying
        soft-gate scan path (stan)."""
        from jax.sharding import Mesh

        from hhmm_tpu.infer import GibbsConfig
        from hhmm_tpu.models import TayalHHMM
        from hhmm_tpu.models.tayal import _UP_STATES
        from hhmm_tpu.sim import obsmodel_categorical

        devices = jax.devices()
        if len(devices) < 8:
            pytest.skip("needs 8 virtual devices")
        rng = np.random.default_rng(2)
        model = TayalHHMM(gate_mode=gate_mode)
        A = np.array(
            [[0.0, 0.4, 0.6, 0.0], [1.0, 0.0, 0.0, 0.0],
             [0.3, 0.0, 0.0, 0.7], [0.0, 0.0, 1.0, 0.0]]
        )
        p1 = np.array([0.5, 0.0, 0.5, 0.0])
        B, T = 8, 160
        xs, signs = [], []
        for i in range(B):
            phi = rng.dirichlet(np.ones(9), size=4)
            z, x = hmm_sim(
                jax.random.PRNGKey(100 + i), T, A, p1,
                obsmodel_categorical(phi), validate=False,
            )
            sign = np.where(_UP_STATES[np.asarray(z)], 0, 1).astype(np.int32)
            if gate_mode == "stan":
                # soft gate is the real-tick semantics: inject
                # same-sign restarts so the time-varying kernel is
                # actually exercised
                for t in np.flatnonzero(rng.random(T) < 0.3)[1:]:
                    sign[t] = sign[t - 1]
            xs.append(np.asarray(x, np.int32))
            signs.append(sign)
        data = {"x": np.stack(xs), "sign": np.stack(signs)}
        cfg = GibbsConfig(num_warmup=10, num_samples=25, num_chains=2)
        mesh = Mesh(np.asarray(devices[:8]).reshape(8, 1)[:, 0], ("series",))
        qs_sharded, st_s = fit_batched(
            model, data, jax.random.PRNGKey(0), cfg, chunk_size=8, mesh=mesh
        )
        qs_plain, st_p = fit_batched(
            model, data, jax.random.PRNGKey(0), cfg, chunk_size=8
        )
        assert np.isfinite(np.asarray(st_s["logp"])).all()
        np.testing.assert_allclose(
            np.asarray(qs_sharded), np.asarray(qs_plain), rtol=1e-5, atol=1e-5
        )

    def test_mesh_sharded_tree_gibbs(self):
        """Route-augmented tree Gibbs (hhmm/routes.py) over the series
        mesh: sharded draws must equal the single-device draws — the
        route gathers, segment-Dirichlet, and MH sigma steps are all
        per-series independent."""
        from jax.sharding import Mesh

        from hhmm_tpu.hhmm.examples import hier2x2_tree
        from hhmm_tpu.hhmm.simulate import hhmm_sim
        from hhmm_tpu.infer import GibbsConfig
        from hhmm_tpu.models import TreeHMM

        devices = jax.devices()
        if len(devices) < 8:
            pytest.skip("needs 8 virtual devices")
        rng = np.random.default_rng(3)
        model = TreeHMM(hier2x2_tree(), order_mu="none")
        data = {
            "x": np.stack(
                [hhmm_sim(hier2x2_tree(), T=80, rng=rng)[1] for _ in range(8)]
            ).astype(np.float32)
        }
        cfg = GibbsConfig(num_warmup=10, num_samples=25, num_chains=2)
        mesh = Mesh(np.asarray(devices[:8]).reshape(8, 1)[:, 0], ("series",))
        qs_sharded, st_s = fit_batched(
            model, data, jax.random.PRNGKey(0), cfg, chunk_size=8, mesh=mesh
        )
        qs_plain, st_p = fit_batched(
            model, data, jax.random.PRNGKey(0), cfg, chunk_size=8
        )
        assert np.isfinite(np.asarray(st_s["logp"])).all()
        np.testing.assert_allclose(
            np.asarray(qs_sharded), np.asarray(qs_plain), rtol=1e-5, atol=1e-5
        )

    def test_warm_start_init(self):
        """Explicit init (walk-forward warm start) is honored."""
        T = 150
        x = _series(jax.random.PRNGKey(5), T)
        model = GaussianHMM(K=2)
        init = jnp.stack(
            [
                jnp.stack(
                    [
                        model.init_unconstrained(k, {"x": x})
                        for k in jax.random.split(jax.random.PRNGKey(9), 2)
                    ]
                )
            ]
        )
        qs, _ = fit_batched(model, {"x": x[None]}, jax.random.PRNGKey(0), CFG, init=init)
        assert qs.shape[:2] == (1, 2)


class TestChunkRetry:
    def test_unavailable_retries_then_succeeds(self, tmp_path, monkeypatch):
        """Device faults (UNAVAILABLE — the tunnel drops executions
        mid-sweep) are retried per chunk instead of killing the sweep;
        non-UNAVAILABLE errors propagate immediately."""
        import hhmm_tpu.batch.fit as fit_mod

        B, T = 2, 120
        xs = np.stack([_series(jax.random.PRNGKey(i), T) for i in range(B)])
        model = GaussianHMM(K=2)
        cfg = SamplerConfig(num_warmup=30, num_samples=20, num_chains=1, max_treedepth=4)

        real_block = fit_mod.jax.block_until_ready
        fails = {"n": 2}

        def flaky(x):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise ValueError("UNAVAILABLE: TPU device error (injected)")
            return real_block(x)

        monkeypatch.setattr(fit_mod.jax, "block_until_ready", flaky)
        monkeypatch.setattr(fit_mod, "_RETRY_SLEEP_S", 0.0, raising=False)
        qs, _ = fit_batched(
            model, {"x": xs}, jax.random.PRNGKey(0), cfg,
            chunk_size=2, cache_dir=str(tmp_path),
        )
        assert fails["n"] == 0
        assert qs.shape[0] == B

    def test_other_errors_propagate(self, tmp_path, monkeypatch):
        import hhmm_tpu.batch.fit as fit_mod

        B, T = 2, 120
        xs = np.stack([_series(jax.random.PRNGKey(i), T) for i in range(B)])
        model = GaussianHMM(K=2)
        cfg = SamplerConfig(num_warmup=30, num_samples=20, num_chains=1, max_treedepth=4)

        def broken(x):
            raise RuntimeError("INTERNAL: something else")

        monkeypatch.setattr(fit_mod.jax, "block_until_ready", broken)
        with pytest.raises(RuntimeError, match="INTERNAL"):
            fit_batched(
                model, {"x": xs}, jax.random.PRNGKey(0), cfg,
                chunk_size=2, cache_dir=str(tmp_path),
            )
