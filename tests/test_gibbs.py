"""Blocked conjugate Gibbs sampler tests (`infer/gibbs.py`).

Validation mirrors the other samplers (SURVEY.md §4 discipline):
cross-sampler posterior agreement against NUTS on the identical
posterior, SBC rank uniformity through the batched engine, and the
guard rails (non-conjugate gate mode, models without a conjugate
block).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import kstest

from hhmm_tpu.batch import fit_batched
from hhmm_tpu.infer import (
    GibbsConfig,
    SamplerConfig,
    init_chains,
    sample_gibbs,
    sample_nuts,
)
from hhmm_tpu.models import GaussianHMM, MultinomialHMM, TayalHHMM
from hhmm_tpu.models.tayal import _UP_STATES, UP
from hhmm_tpu.sim import hmm_sim, obsmodel_categorical


class TestGuards:
    def test_requires_gibbs_update(self):
        from hhmm_tpu.models import IOHMMReg

        with pytest.raises(ValueError, match="gibbs_update"):
            sample_gibbs(
                IOHMMReg(K=2, M=2),
                {"x": np.zeros(10, np.float32), "u": np.zeros((10, 2), np.float32)},
                jax.random.PRNGKey(0),
            )

    def test_gaussian_requires_proper_prior(self):
        with pytest.raises(ValueError, match="nig_prior"):
            sample_gibbs(
                GaussianHMM(K=2),
                {"x": np.zeros(10, np.float32)},
                jax.random.PRNGKey(0),
                GibbsConfig(num_warmup=1, num_samples=1),
            )

    def test_rejects_stan_gate(self):
        with pytest.raises(ValueError, match="hard"):
            sample_gibbs(
                TayalHHMM(gate_mode="stan"),
                {"x": np.zeros(10, np.int32), "sign": np.zeros(10, np.int32)},
                jax.random.PRNGKey(0),
            )


class TestCrossSamplerAgreement:
    def test_matches_nuts_on_multinomial_hmm(self):
        """Gibbs and NUTS target the identical flat-prior posterior;
        pooled canonicalized posterior means must agree to MC error."""
        K, L, T = 2, 3, 300
        model = MultinomialHMM(K=K, L=L)
        A = np.array([[0.85, 0.15], [0.25, 0.75]])
        p1 = np.array([0.6, 0.4])
        phi = np.array([[0.7, 0.2, 0.1], [0.1, 0.3, 0.6]])
        z, x = hmm_sim(
            jax.random.PRNGKey(5), T, A, p1, obsmodel_categorical(phi), validate=False
        )
        data = {"x": np.asarray(x, np.int32)}

        def canon(qs):
            d = model.constrained_draws(qs.reshape(-1, qs.shape[-1]))
            phid = np.asarray(d["phi_k"]).reshape(-1, K, L)
            Ad = np.asarray(d["A_ij"]).reshape(-1, K, K)
            o = np.argsort(phid[:, :, 0], axis=1)
            i = np.arange(len(phid))[:, None]
            phid = phid[i, o]
            Ad = Ad[i[:, :, None], o[:, :, None], o[:, None, :]]
            return np.concatenate([phid.mean(0).ravel(), Ad.mean(0).ravel()])

        qg, sg = sample_gibbs(
            model, data, jax.random.PRNGKey(0),
            GibbsConfig(num_warmup=200, num_samples=800, num_chains=2),
        )
        qn, _ = sample_nuts(
            model.make_logp({"x": jnp.asarray(data["x"])}),
            jax.random.PRNGKey(0),
            init_chains(model, jax.random.PRNGKey(1), data, 2),
            SamplerConfig(num_warmup=250, num_samples=400, num_chains=2, max_treedepth=6),
        )
        assert np.isfinite(np.asarray(sg["logp"])).all()
        np.testing.assert_allclose(canon(qg), canon(qn), atol=0.05)

    def test_matches_nuts_on_gaussian_hmm(self):
        """NIG-prior Gaussian HMM: Gibbs (FFBS + joint NIG block with
        ordered-cone accept step) and NUTS with the same ``log_prior``
        target the identical posterior (`hmm/stan/hmm.stan:14-46`
        semantics + the conjugate prior both samplers share)."""
        from hhmm_tpu.models import NIGPrior
        from hhmm_tpu.sim import obsmodel_gaussian

        K, T = 2, 400
        prior = NIGPrior(m0=0.0, kappa0=0.2, a0=2.5, b0=1.5)
        model = GaussianHMM(K=K, nig_prior=prior)
        A = np.array([[0.9, 0.1], [0.2, 0.8]])
        p1 = np.array([0.5, 0.5])
        mu = np.array([-1.5, 1.5])
        sigma = np.array([0.6, 0.9])
        z, x = hmm_sim(
            jax.random.PRNGKey(3), T, A, p1, obsmodel_gaussian(mu, sigma), validate=False
        )
        data = {"x": np.asarray(x, np.float32)}

        def moments(qs):
            d = model.constrained_draws(qs.reshape(-1, qs.shape[-1]))
            return np.concatenate(
                [
                    np.asarray(d["mu_k"]).mean(0),
                    np.asarray(d["sigma_k"]).mean(0),
                    np.asarray(d["A_ij"]).reshape(-1, K * K).mean(0),
                    np.asarray(d["mu_k"]).std(0),
                ]
            )

        qg, sg = sample_gibbs(
            model, data, jax.random.PRNGKey(0),
            GibbsConfig(num_warmup=200, num_samples=800, num_chains=2),
        )
        qn, _ = sample_nuts(
            model.make_logp({"x": jnp.asarray(data["x"])}),
            jax.random.PRNGKey(0),
            init_chains(model, jax.random.PRNGKey(1), data, 2),
            SamplerConfig(num_warmup=250, num_samples=400, num_chains=2, max_treedepth=6),
        )
        assert np.isfinite(np.asarray(sg["logp"])).all()
        np.testing.assert_allclose(moments(qg), moments(qn), atol=0.07)
        # recovery sanity on the same fit
        d = model.constrained_draws(qg.reshape(-1, qg.shape[-1]))
        np.testing.assert_allclose(np.asarray(d["mu_k"]).mean(0), mu, atol=0.35)


class TestSBCGibbs:
    def test_rank_uniformity_tayal(self, rng):
        """SBC through fit_batched with the Gibbs sampler on the Tayal
        hard-gate model (the bench.py --sampler gibbs path): ranks of
        prior draws among posterior draws must be uniform."""
        N_REPS, THIN = 12, 4
        model = TayalHHMM(gate_mode="hard")
        datasets, trues = [], []
        for _ in range(N_REPS):
            p11 = rng.uniform()
            A_row = rng.dirichlet(np.ones(2), size=2)
            phi = rng.dirichlet(np.ones(9), size=4)
            params = {
                "p_11": jnp.asarray(p11),
                "A_row": jnp.asarray(A_row),
                "phi_k": jnp.asarray(phi),
            }
            pi, A = model.assemble(params)
            z, x = hmm_sim(
                jax.random.PRNGKey(int(rng.integers(1 << 30))),
                300,
                np.asarray(A),
                np.asarray(pi),
                obsmodel_categorical(phi),
                validate=False,
            )
            sign = np.where(_UP_STATES[np.asarray(z)], UP, 1 - UP)
            datasets.append(
                {
                    "x": np.asarray(x, np.int32),
                    "sign": sign.astype(np.int32),
                    "mask": np.ones(300, np.float32),
                }
            )
            trues.append(
                np.concatenate([[p11], [A_row[0, 0], A_row[1, 0]], phi[:, 0], [phi[2, 4]]])
            )
        data = {k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]}
        cfg = GibbsConfig(num_warmup=100, num_samples=400, num_chains=1)
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(0), cfg, chunk_size=N_REPS)

        units = []
        for i in range(N_REPS):
            draws = model.constrained_draws(qs[i].reshape(-1, qs.shape[-1]))
            flat = np.column_stack(
                [
                    np.asarray(draws["p_11"]).reshape(-1),
                    np.asarray(draws["A_row"]).reshape(-1, 4)[:, 0],
                    np.asarray(draws["A_row"]).reshape(-1, 4)[:, 2],
                    *[np.asarray(draws["phi_k"]).reshape(-1, 4, 9)[:, k, 0] for k in range(4)],
                    np.asarray(draws["phi_k"]).reshape(-1, 4, 9)[:, 2, 4],
                ]
            )
            thinned = flat[::THIN]
            r = (thinned < trues[i][None, :]).sum(axis=0)
            units.append((r + 0.5) / (thinned.shape[0] + 1))
        u = np.concatenate(units)
        assert 0.30 < u.mean() < 0.70, f"rank mean {u.mean():.3f}"
        p = kstest(u, "uniform").pvalue
        assert p > 1e-3, f"KS uniformity p={p:.2e}"


class TestWalkForwardGibbs:
    def test_tayal_wf_trade_with_gibbs(self, tmp_path, tayal_wf_tasks):
        """The Tayal walk-forward harness runs end-to-end with the Gibbs
        sampler: TayalHHMMLite inherits the conjugate block, hard gate
        gives the exact factorization, and fit_batched dispatches on
        GibbsConfig."""
        from hhmm_tpu.apps.tayal import wf_trade

        results = wf_trade(
            tayal_wf_tasks,
            config=GibbsConfig(num_warmup=50, num_samples=150, num_chains=1),
            gate_mode="hard",
            chunk_size=4,
            cache_dir=str(tmp_path),
        )
        assert len(results) == 4
        for r in results:
            assert np.isfinite(r.bnh).all()
            assert set(r.trades.keys()) == {0, 1, 2, 3, 4, 5}


class TestMaskedEquivalence:
    def test_padded_matches_truncated_counts(self):
        """The conjugate count helpers must ignore padded steps: a
        padded series gives identical count matrices to the truncated
        one (the invariant the masked loglik already satisfies)."""
        from hhmm_tpu.infer.gibbs import emission_counts, transition_counts

        rng = np.random.default_rng(0)
        T, K, L = 50, 3, 4
        z = jnp.asarray(rng.integers(0, K, T), jnp.int32)
        x = jnp.asarray(rng.integers(0, L, T), jnp.int32)
        z_pad = jnp.concatenate([z, jnp.full(10, z[-1], jnp.int32)])
        x_pad = jnp.concatenate([x, jnp.zeros(10, jnp.int32)])
        mask = jnp.concatenate([jnp.ones(T), jnp.zeros(10)]).astype(jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(transition_counts(z_pad, K, mask)),
            np.asarray(transition_counts(z, K, None)),
        )
        np.testing.assert_array_equal(
            np.asarray(emission_counts(z_pad, x_pad, K, L, mask)),
            np.asarray(emission_counts(z, x, K, L, None)),
        )
