"""Blocked conjugate Gibbs sampler tests (`infer/gibbs.py`).

Validation mirrors the other samplers (SURVEY.md §4 discipline):
cross-sampler posterior agreement against NUTS on the identical
posterior, SBC rank uniformity through the batched engine, and the
guard rails (non-conjugate gate mode, models without a conjugate
block).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import kstest

from hhmm_tpu.batch import fit_batched
from hhmm_tpu.infer import (
    GibbsConfig,
    SamplerConfig,
    init_chains,
    sample_gibbs,
    sample_nuts,
)
from hhmm_tpu.models import GaussianHMM, MultinomialHMM, TayalHHMM
from hhmm_tpu.models.tayal import _UP_STATES, UP
from hhmm_tpu.sim import hmm_sim, obsmodel_categorical


class TestGuards:
    def test_requires_gibbs_update(self):
        from hhmm_tpu.models import IOHMMReg

        with pytest.raises(ValueError, match="gibbs_update"):
            sample_gibbs(
                IOHMMReg(K=2, M=2),
                {"x": np.zeros(10, np.float32), "u": np.zeros((10, 2), np.float32)},
                jax.random.PRNGKey(0),
            )

    def test_gaussian_requires_proper_prior(self):
        with pytest.raises(ValueError, match="nig_prior"):
            sample_gibbs(
                GaussianHMM(K=2),
                {"x": np.zeros(10, np.float32)},
                jax.random.PRNGKey(0),
                GibbsConfig(num_warmup=1, num_samples=1),
            )

    def test_rejects_undeclared_gate(self):
        """A gated model whose gibbs_update does not declare the active
        gate mode must be rejected (not-actually-conjugate combinations
        fail loudly)."""

        class HardOnlyTayal(TayalHHMM):
            gibbs_gate_modes = ("hard",)

        with pytest.raises(ValueError, match="gate_mode"):
            sample_gibbs(
                HardOnlyTayal(gate_mode="stan"),
                {"x": np.zeros(10, np.int32), "sign": np.zeros(10, np.int32)},
                jax.random.PRNGKey(0),
            )


class TestCrossSamplerAgreement:
    @pytest.mark.slow
    def test_matches_nuts_on_multinomial_hmm(self):
        """Gibbs and NUTS target the identical flat-prior posterior;
        pooled canonicalized posterior means must agree to MC error."""
        K, L, T = 2, 3, 300
        model = MultinomialHMM(K=K, L=L)
        A = np.array([[0.85, 0.15], [0.25, 0.75]])
        p1 = np.array([0.6, 0.4])
        phi = np.array([[0.7, 0.2, 0.1], [0.1, 0.3, 0.6]])
        z, x = hmm_sim(
            jax.random.PRNGKey(5), T, A, p1, obsmodel_categorical(phi), validate=False
        )
        data = {"x": np.asarray(x, np.int32)}

        def canon(qs):
            d = model.constrained_draws(qs.reshape(-1, qs.shape[-1]))
            phid = np.asarray(d["phi_k"]).reshape(-1, K, L)
            Ad = np.asarray(d["A_ij"]).reshape(-1, K, K)
            o = np.argsort(phid[:, :, 0], axis=1)
            i = np.arange(len(phid))[:, None]
            phid = phid[i, o]
            Ad = Ad[i[:, :, None], o[:, :, None], o[:, None, :]]
            return np.concatenate([phid.mean(0).ravel(), Ad.mean(0).ravel()])

        qg, sg = sample_gibbs(
            model, data, jax.random.PRNGKey(0),
            GibbsConfig(num_warmup=200, num_samples=800, num_chains=2),
        )
        qn, _ = sample_nuts(
            model.make_logp({"x": jnp.asarray(data["x"])}),
            jax.random.PRNGKey(0),
            init_chains(model, jax.random.PRNGKey(1), data, 2),
            SamplerConfig(num_warmup=250, num_samples=400, num_chains=2, max_treedepth=6),
        )
        assert np.isfinite(np.asarray(sg["logp"])).all()
        np.testing.assert_allclose(canon(qg), canon(qn), atol=0.05)

    @pytest.mark.slow
    def test_matches_nuts_on_gaussian_hmm(self):
        """NIG-prior Gaussian HMM: Gibbs (FFBS + joint NIG block with
        ordered-cone accept step) and NUTS with the same ``log_prior``
        target the identical posterior (`hmm/stan/hmm.stan:14-46`
        semantics + the conjugate prior both samplers share)."""
        from hhmm_tpu.models import NIGPrior
        from hhmm_tpu.sim import obsmodel_gaussian

        K, T = 2, 400
        prior = NIGPrior(m0=0.0, kappa0=0.2, a0=2.5, b0=1.5)
        model = GaussianHMM(K=K, nig_prior=prior)
        A = np.array([[0.9, 0.1], [0.2, 0.8]])
        p1 = np.array([0.5, 0.5])
        mu = np.array([-1.5, 1.5])
        sigma = np.array([0.6, 0.9])
        z, x = hmm_sim(
            jax.random.PRNGKey(3), T, A, p1, obsmodel_gaussian(mu, sigma), validate=False
        )
        data = {"x": np.asarray(x, np.float32)}

        def moments(qs):
            d = model.constrained_draws(qs.reshape(-1, qs.shape[-1]))
            return np.concatenate(
                [
                    np.asarray(d["mu_k"]).mean(0),
                    np.asarray(d["sigma_k"]).mean(0),
                    np.asarray(d["A_ij"]).reshape(-1, K * K).mean(0),
                    np.asarray(d["mu_k"]).std(0),
                ]
            )

        qg, sg = sample_gibbs(
            model, data, jax.random.PRNGKey(0),
            GibbsConfig(num_warmup=200, num_samples=800, num_chains=2),
        )
        qn, _ = sample_nuts(
            model.make_logp({"x": jnp.asarray(data["x"])}),
            jax.random.PRNGKey(0),
            init_chains(model, jax.random.PRNGKey(1), data, 2),
            SamplerConfig(num_warmup=250, num_samples=400, num_chains=2, max_treedepth=6),
        )
        assert np.isfinite(np.asarray(sg["logp"])).all()
        np.testing.assert_allclose(moments(qg), moments(qn), atol=0.07)
        # recovery sanity on the same fit
        d = model.constrained_draws(qg.reshape(-1, qg.shape[-1]))
        np.testing.assert_allclose(np.asarray(d["mu_k"]).mean(0), mu, atol=0.35)


def _nonalternating_tayal_data(rng, T=240, frac_same=0.3):
    """Synthetic (x, sign) with ~``frac_same`` same-sign adjacent legs —
    the real-tick regime (flat stretches restart a leg in the same
    direction, `feature-extraction.R:27-29`) where the hard gate is
    invalid and the stan soft gate is the semantics under test."""
    model = TayalHHMM(gate_mode="hard")
    phi = np.array(
        [rng.dirichlet(np.ones(9) * c) for c in (0.4, 0.4, 0.4, 0.4)]
    )
    params = {
        "p_11": jnp.asarray(0.6),
        "A_row": jnp.asarray(rng.dirichlet(np.ones(2), size=2)),
        "phi_k": jnp.asarray(phi),
    }
    pi, A = model.assemble(params)
    z, x = hmm_sim(
        jax.random.PRNGKey(int(rng.integers(1 << 30))),
        T,
        np.asarray(A),
        np.asarray(pi),
        obsmodel_categorical(phi),
        validate=False,
    )
    sign = np.where(_UP_STATES[np.asarray(z)], UP, 1 - UP).astype(np.int32)
    # inject same-sign restarts: copy the previous leg's sign at random
    # interior positions
    flip = rng.random(T) < frac_same
    flip[0] = False
    for t in np.flatnonzero(flip):
        sign[t] = sign[t - 1]
    assert (sign[1:] == sign[:-1]).mean() > 0.15
    return np.asarray(x, np.int32), sign


def _simplex64(v):
    """f32 simplex -> f64 renormalized (scipy.stats.dirichlet enforces
    sum == 1 beyond f32 round-off)."""
    v = np.asarray(v, np.float64)
    return v / v.sum()


class TestStanGateConjugacy:
    """Exactness of the soft-gate blocked Gibbs (the semantics fit to
    real ticks): z | θ via enumeration, θ | z via density ratios."""

    def _logjoint(self, model, params, z, data):
        """log of the augmented joint factorization defined by
        ``model.build`` (flat priors: constant in θ, cancels in
        ratios)."""
        log_pi, log_A, log_obs, _ = model.build(params, data)
        log_A = np.asarray(log_A)
        z = np.asarray(z)
        lp = float(np.asarray(log_pi)[z[0]] + np.asarray(log_obs)[0, z[0]])
        for t in range(1, len(z)):
            A_t = log_A[t - 1] if log_A.ndim == 3 else log_A
            lp += float(A_t[z[t - 1], z[t]] + np.asarray(log_obs)[t, z[t]])
        return lp

    def test_tayal_stan_theta_conditional_density_ratio(self, rng):
        """For fixed z the claimed Beta/Dirichlet conditional must be
        proportional to the joint: log-ratio in θ of the joint equals
        the log-ratio of the conditional, for random θ pairs — an
        exact (non-statistical) check of the consistency-weighted
        sufficient statistics."""
        from scipy.stats import beta as sp_beta, dirichlet as sp_dir

        from hhmm_tpu.kernels.ffbs import backward_sample
        from hhmm_tpu.kernels.filtering import forward_filter

        model = TayalHHMM(gate_mode="stan")
        x, sign = _nonalternating_tayal_data(rng)
        data = {"x": jnp.asarray(x), "sign": jnp.asarray(sign)}
        T = len(x)

        def rand_params():
            return {
                "p_11": jnp.asarray(rng.uniform(0.1, 0.9)),
                "A_row": jnp.asarray(rng.dirichlet(np.ones(2), size=2)),
                "phi_k": jnp.asarray(rng.dirichlet(np.ones(9), size=4)),
            }

        def log_q(params, z):
            """Independent re-derivation of the claimed conditional."""
            cons = (sign == UP) == _UP_STATES[np.asarray(z)]
            n = np.zeros((4, 4))
            for t in range(1, T):
                if cons[t]:
                    n[z[t - 1], z[t]] += 1
            c = np.zeros((4, 9))
            for t in range(T):
                c[z[t], x[t]] += 1
            a = 1.0 + float(sign[0] == 1 and z[0] == 0)
            b = 1.0 + float(sign[0] == 0 and z[0] == 2)
            lq = sp_beta.logpdf(float(params["p_11"]), a, b)
            Ar = np.asarray(params["A_row"])
            lq += sp_dir.logpdf(_simplex64(Ar[0]), 1.0 + np.array([n[0, 1], n[0, 2]]))
            lq += sp_dir.logpdf(_simplex64(Ar[1]), 1.0 + np.array([n[2, 0], n[2, 3]]))
            phi = np.asarray(params["phi_k"])
            for k in range(4):
                lq += sp_dir.logpdf(_simplex64(phi[k]), 1.0 + c[k])
            return lq

        # z from FFBS at a reference θ: guarantees positive support
        # under every θ (the sparse-A zero pattern is θ-independent)
        p0 = rand_params()
        log_pi, log_A_t, log_obs, _ = model.build(p0, data)
        log_alpha, _ = forward_filter(log_pi, log_A_t, log_obs, None)
        for i in range(3):
            z = backward_sample(jax.random.PRNGKey(i), log_alpha, log_A_t, None)
            t1, t2 = rand_params(), rand_params()
            lhs = self._logjoint(model, t1, z, data) - self._logjoint(
                model, t2, z, data
            )
            rhs = log_q(t1, z) - log_q(t2, z)
            assert abs(lhs - rhs) < 5e-2, f"draw {i}: joint ratio {lhs} vs conditional ratio {rhs}"

    def test_semisup_stan_theta_conditional_density_ratio(self, rng):
        """Same exactness check for the semisup multinomial soft gate
        (`hmm-multinom-semisup.stan:42-44`): ungated p_1k, consistency-
        weighted transition counts."""
        from scipy.stats import dirichlet as sp_dir

        from hhmm_tpu.models import SemisupMultinomialHMM

        K, L, T = 4, 5, 150
        groups = np.array([0, 1, 1, 0], np.int32)
        model = SemisupMultinomialHMM(K=K, L=L, groups=groups, gate_mode="stan")
        x = rng.integers(0, L, T).astype(np.int32)
        g = rng.integers(0, 2, T).astype(np.int32)
        data = {"x": jnp.asarray(x), "g": jnp.asarray(g)}

        def rand_params():
            return {
                "p_1k": jnp.asarray(rng.dirichlet(np.ones(K))),
                "A_ij": jnp.asarray(rng.dirichlet(np.ones(K), size=K)),
                "phi_k": jnp.asarray(rng.dirichlet(np.ones(L), size=K)),
            }

        def log_q(params, z):
            cons = g == groups[np.asarray(z)]
            n = np.zeros((K, K))
            for t in range(1, T):
                if cons[t]:
                    n[z[t - 1], z[t]] += 1
            c = np.zeros((K, L))
            for t in range(T):
                c[z[t], x[t]] += 1
            lq = sp_dir.logpdf(
                _simplex64(params["p_1k"]),
                1.0 + np.eye(K)[int(z[0])],
            )
            for k in range(K):
                lq += sp_dir.logpdf(_simplex64(np.asarray(params["A_ij"])[k]), 1.0 + n[k])
                lq += sp_dir.logpdf(_simplex64(np.asarray(params["phi_k"])[k]), 1.0 + c[k])
            return lq

        for i in range(3):
            z = rng.integers(0, K, T)  # full support: any z is valid here
            t1, t2 = rand_params(), rand_params()
            lhs = self._logjoint(model, t1, z, data) - self._logjoint(
                model, t2, z, data
            )
            rhs = log_q(t1, z) - log_q(t2, z)
            assert abs(lhs - rhs) < 5e-2, f"draw {i}: {lhs} vs {rhs}"

    def test_gated_ffbs_matches_enumeration(self, rng):
        """z | θ under the time-varying gated kernel: FFBS pairwise
        frequencies must match the brute-force posterior over all 4^T
        paths of the build's factorization."""
        from itertools import product

        from scipy.special import logsumexp as lse

        from hhmm_tpu.kernels.ffbs import backward_sample
        from hhmm_tpu.kernels.filtering import forward_filter

        model = TayalHHMM(gate_mode="stan")
        T = 6
        x = rng.integers(0, 9, T).astype(np.int32)
        sign = np.array([1, 0, 0, 1, 1, 0], np.int32)  # non-alternating
        data = {"x": jnp.asarray(x), "sign": jnp.asarray(sign)}
        params = {
            "p_11": jnp.asarray(0.55),
            "A_row": jnp.asarray(rng.dirichlet(np.ones(2), size=2)),
            "phi_k": jnp.asarray(rng.dirichlet(np.ones(9), size=4)),
        }
        log_pi, log_A_t, log_obs, _ = model.build(params, data)
        lp_np, lA_np, lo_np = map(np.asarray, (log_pi, log_A_t, log_obs))
        logp = {}
        for path in product(range(4), repeat=T):
            lp = lp_np[path[0]] + lo_np[0, path[0]]
            for t in range(1, T):
                lp += lA_np[t - 1, path[t - 1], path[t]] + lo_np[t, path[t]]
            if np.isfinite(lp):
                logp[path] = lp
        total = lse(np.array(list(logp.values())))
        pair = np.zeros((4, 4))
        for path, lp in logp.items():
            pair[path[2], path[3]] += np.exp(lp - total)

        log_alpha, _ = forward_filter(log_pi, log_A_t, log_obs, None)
        n = 8000
        paths = np.asarray(
            jax.vmap(lambda k: backward_sample(k, log_alpha, log_A_t, None))(
                jax.random.split(jax.random.PRNGKey(2), n)
            )
        )
        emp = np.zeros((4, 4))
        for a in range(4):
            for b in range(4):
                emp[a, b] = np.mean((paths[:, 2] == a) & (paths[:, 3] == b))
        np.testing.assert_allclose(emp, pair, atol=0.03)

    @pytest.mark.slow
    def test_semisup_gibbs_matches_nuts_on_stan_gate(self, rng):
        """Cross-sampler agreement for the semisup soft gate: the
        consistency-weighted conjugate block must target the same
        posterior NUTS integrates on the identical gated density —
        including steps whose observed group contradicts every
        high-emission state (the gate's unit-factor track)."""
        from hhmm_tpu.models import SemisupMultinomialHMM

        K, L, T = 4, 5, 300
        groups = np.array([0, 1, 1, 0], np.int32)
        A = np.array(
            [[0.7, 0.1, 0.1, 0.1], [0.1, 0.7, 0.1, 0.1],
             [0.1, 0.1, 0.7, 0.1], [0.1, 0.1, 0.1, 0.7]]
        )
        phi = np.array(
            [[0.6, 0.2, 0.1, 0.05, 0.05], [0.05, 0.6, 0.2, 0.1, 0.05],
             [0.05, 0.05, 0.6, 0.2, 0.1], [0.1, 0.05, 0.05, 0.6, 0.2]]
        )
        z, x = hmm_sim(
            jax.random.PRNGKey(9), T, A, np.ones(K) / K,
            obsmodel_categorical(phi), validate=False,
        )
        g = groups[np.asarray(z)].copy()
        # corrupt ~15% of labels: group evidence that fights the
        # emissions exercises the soft gate's unit-factor branch
        flip = rng.random(T) < 0.15
        g[flip] = 1 - g[flip]
        model = SemisupMultinomialHMM(K=K, L=L, groups=groups, gate_mode="stan")
        data = {"x": np.asarray(x, np.int32), "g": g.astype(np.int32)}

        def canon(qs):
            d = model.constrained_draws(qs.reshape(-1, qs.shape[-1]))
            phid = np.asarray(d["phi_k"]).reshape(-1, K, L)
            # canonicalize within each group's state pair by first-symbol
            # ordering (label switching is within-group here: the gate
            # pins group identity)
            out = []
            for pair in ([0, 3], [1, 2]):
                sub = phid[:, pair, :]
                o = np.argsort(sub[:, :, 0], axis=1)
                i = np.arange(len(sub))[:, None]
                out.append(sub[i, o].mean(0).ravel())
            return np.concatenate(out)

        qg, sg = sample_gibbs(
            model, data, jax.random.PRNGKey(0),
            GibbsConfig(num_warmup=300, num_samples=1200, num_chains=2),
        )
        qn, _ = sample_nuts(
            model.make_logp({k: jnp.asarray(v) for k, v in data.items()}),
            jax.random.PRNGKey(2),
            init_chains(model, jax.random.PRNGKey(1), data, 2),
            SamplerConfig(num_warmup=300, num_samples=500, num_chains=2,
                          max_treedepth=6),
        )
        assert np.isfinite(np.asarray(sg["logp"])).all()
        np.testing.assert_allclose(canon(qg), canon(qn), atol=0.06)

    @pytest.mark.slow
    def test_gibbs_matches_chees_on_stan_gate(self, rng):
        """Cross-sampler agreement on the soft-gate density with
        non-alternating data — the pair (z|θ exact FFBS, θ|z conjugate)
        must target the same posterior the HMC samplers integrate."""
        from hhmm_tpu.infer import ChEESConfig, sample_chees

        model = TayalHHMM(gate_mode="stan")
        x, sign = _nonalternating_tayal_data(rng, T=300)
        data = {"x": jnp.asarray(x), "sign": jnp.asarray(sign)}

        def canon(qs):
            """Per-draw pair-swap fold (states (0,1,2,3)->(3,2,1,0)) —
            an EMPIRICAL mode fold, not an exact likelihood symmetry
            (the sparse A is asymmetric under it; see bench.py). Any
            fixed measurable function of draws is a valid agreement
            statistic; the fold just merges the near-symmetric modes to
            cut MC variance. Orient by the two up-leg rows' first
            symbol."""
            d = model.constrained_draws(qs.reshape(-1, qs.shape[-1]))
            phi = np.asarray(d["phi_k"])
            swap = phi[:, 1, 0] < phi[:, 2, 0]
            phi_c = np.where(swap[:, None, None], phi[:, [3, 2, 1, 0], :], phi)
            Ar = np.asarray(d["A_row"])
            Ar_c = np.where(swap[:, None, None], Ar[:, [1, 0], :], Ar)
            return np.concatenate([phi_c.mean(0).ravel(), Ar_c.mean(0).ravel()])

        qg, sg = sample_gibbs(
            model, data, jax.random.PRNGKey(0),
            GibbsConfig(num_warmup=300, num_samples=1200, num_chains=2),
        )
        qc, _ = sample_chees(
            model.make_logp(data),
            jax.random.PRNGKey(3),
            init_chains(model, jax.random.PRNGKey(1), data, 8),
            ChEESConfig(num_warmup=400, num_samples=400, num_chains=8,
                        max_leapfrogs=32),
        )
        assert np.isfinite(np.asarray(sg["logp"])).all()
        np.testing.assert_allclose(canon(qg), canon(qc), atol=0.06)


class TestSBCGibbs:
    @pytest.mark.slow
    def test_rank_uniformity_tayal(self, rng):
        """SBC through fit_batched with the Gibbs sampler on the Tayal
        hard-gate model (the bench.py --sampler gibbs path): ranks of
        prior draws among posterior draws must be uniform."""
        N_REPS, THIN = 12, 4
        model = TayalHHMM(gate_mode="hard")
        datasets, trues = [], []
        for _ in range(N_REPS):
            p11 = rng.uniform()
            A_row = rng.dirichlet(np.ones(2), size=2)
            phi = rng.dirichlet(np.ones(9), size=4)
            params = {
                "p_11": jnp.asarray(p11),
                "A_row": jnp.asarray(A_row),
                "phi_k": jnp.asarray(phi),
            }
            pi, A = model.assemble(params)
            z, x = hmm_sim(
                jax.random.PRNGKey(int(rng.integers(1 << 30))),
                300,
                np.asarray(A),
                np.asarray(pi),
                obsmodel_categorical(phi),
                validate=False,
            )
            sign = np.where(_UP_STATES[np.asarray(z)], UP, 1 - UP)
            datasets.append(
                {
                    "x": np.asarray(x, np.int32),
                    "sign": sign.astype(np.int32),
                    "mask": np.ones(300, np.float32),
                }
            )
            trues.append(
                np.concatenate([[p11], [A_row[0, 0], A_row[1, 0]], phi[:, 0], [phi[2, 4]]])
            )
        data = {k: jnp.asarray(np.stack([d[k] for d in datasets])) for k in datasets[0]}
        cfg = GibbsConfig(num_warmup=100, num_samples=400, num_chains=1)
        qs, stats = fit_batched(model, data, jax.random.PRNGKey(0), cfg, chunk_size=N_REPS)

        units = []
        for i in range(N_REPS):
            draws = model.constrained_draws(qs[i].reshape(-1, qs.shape[-1]))
            flat = np.column_stack(
                [
                    np.asarray(draws["p_11"]).reshape(-1),
                    np.asarray(draws["A_row"]).reshape(-1, 4)[:, 0],
                    np.asarray(draws["A_row"]).reshape(-1, 4)[:, 2],
                    *[np.asarray(draws["phi_k"]).reshape(-1, 4, 9)[:, k, 0] for k in range(4)],
                    np.asarray(draws["phi_k"]).reshape(-1, 4, 9)[:, 2, 4],
                ]
            )
            thinned = flat[::THIN]
            r = (thinned < trues[i][None, :]).sum(axis=0)
            units.append((r + 0.5) / (thinned.shape[0] + 1))
        u = np.concatenate(units)
        assert 0.30 < u.mean() < 0.70, f"rank mean {u.mean():.3f}"
        p = kstest(u, "uniform").pvalue
        assert p > 1e-3, f"KS uniformity p={p:.2e}"


class TestWalkForwardGibbs:
    @pytest.mark.slow
    def test_tayal_wf_trade_with_gibbs(self, tmp_path, tayal_wf_tasks):
        """The Tayal walk-forward harness runs end-to-end with the Gibbs
        sampler: TayalHHMMLite inherits the conjugate block, hard gate
        gives the exact factorization, and fit_batched dispatches on
        GibbsConfig."""
        from hhmm_tpu.apps.tayal import wf_trade

        results = wf_trade(
            tayal_wf_tasks,
            config=GibbsConfig(num_warmup=50, num_samples=150, num_chains=1),
            gate_mode="hard",
            chunk_size=4,
            cache_dir=str(tmp_path),
        )
        assert len(results) == 4
        for r in results:
            assert np.isfinite(r.bnh).all()
            assert set(r.trades.keys()) == {0, 1, 2, 3, 4, 5}


class TestMaskedEquivalence:
    def test_padded_matches_truncated_counts(self):
        """The conjugate count helpers must ignore padded steps: a
        padded series gives identical count matrices to the truncated
        one (the invariant the masked loglik already satisfies)."""
        from hhmm_tpu.infer.gibbs import emission_counts, transition_counts

        rng = np.random.default_rng(0)
        T, K, L = 50, 3, 4
        z = jnp.asarray(rng.integers(0, K, T), jnp.int32)
        x = jnp.asarray(rng.integers(0, L, T), jnp.int32)
        z_pad = jnp.concatenate([z, jnp.full(10, z[-1], jnp.int32)])
        x_pad = jnp.concatenate([x, jnp.zeros(10, jnp.int32)])
        mask = jnp.concatenate([jnp.ones(T), jnp.zeros(10)]).astype(jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(transition_counts(z_pad, K, mask)),
            np.asarray(transition_counts(z, K, None)),
        )
        np.testing.assert_array_equal(
            np.asarray(emission_counts(z_pad, x_pad, K, L, mask)),
            np.asarray(emission_counts(z, x, K, L, None)),
        )
