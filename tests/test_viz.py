"""Smoke tests for the plot library (SURVEY.md §2.1: the reference's
`common/R/plots.R` and `tayal2009/R/state-plots.R` surfaces). Each plot
must build a Figure with the expected panel count on realistic inputs
and close cleanly — no rendering golden-files, matching the reference's
own (untested) plotting discipline."""

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np
import pytest

from hhmm_tpu import viz
from hhmm_tpu.apps.tayal import (
    extract_features,
    map_to_topstate,
    simulate_ticks,
    topstate_trading,
    expand_to_ticks,
)


@pytest.fixture(autouse=True)
def _close_all():
    yield
    plt.close("all")


@pytest.fixture(scope="module")
def tick_data():
    rng = np.random.default_rng(3)
    price, size, tsec, leg_regime = simulate_ticks(rng, n_legs=120)
    zig = extract_features(price, size, tsec)
    return price, size, tsec, zig


def _bands(mid):
    return np.stack([mid - 1.0, mid, mid + 1.0])


class TestCommonPlots:
    def test_intervals(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=100)
        fig = viz.plot_intervals(x, _bands(3 * x), z=(x > 0).astype(int))
        assert len(fig.axes) == 1

    def test_intervals_bad_bands(self):
        with pytest.raises(ValueError):
            viz.plot_intervals(np.zeros(5), np.zeros((2, 5)))

    def test_seqintervals(self):
        mid = np.sin(np.linspace(0, 6, 80))
        z = (mid > 0).astype(int)
        fig = viz.plot_seqintervals(_bands(mid), z=z, k=1)
        assert len(fig.axes) == 1

    def test_seqintervals_requires_k(self):
        with pytest.raises(ValueError):
            viz.plot_seqintervals(_bands(np.zeros(10)), z=np.zeros(10, int))

    def test_inputoutput(self):
        rng = np.random.default_rng(1)
        T, M = 60, 3
        u = rng.normal(size=(T, M))
        x = u @ rng.normal(size=M)
        fig = viz.plot_inputoutput(x, u, z=rng.integers(0, 2, T))
        assert len(fig.axes) == 2 * (M + 1)

    def test_inputprob(self):
        rng = np.random.default_rng(2)
        T, M, K = 50, 2, 3
        p = rng.dirichlet(np.ones(K), size=T)
        fig = viz.plot_inputprob(rng.normal(size=(T, M)), p)
        assert len(fig.axes) == M * K

    def test_stateprobability(self):
        rng = np.random.default_rng(3)
        N, T, K = 20, 40, 2
        alpha = rng.dirichlet(np.ones(K), size=(N, T))
        gamma = rng.dirichlet(np.ones(K), size=(N, T))
        fig = viz.plot_stateprobability(alpha, gamma, z=rng.integers(0, K, T))
        assert len(fig.axes) == 3

    def test_statepath(self):
        rng = np.random.default_rng(4)
        zstar = rng.integers(0, 3, size=(25, 50))
        fig = viz.plot_statepath(zstar, z=zstar[0])
        assert len(fig.axes) == 2

    def test_outputfit(self):
        rng = np.random.default_rng(5)
        T = 60
        x = np.cumsum(rng.normal(size=T))
        xhat = x + rng.normal(scale=0.3, size=(30, T))
        fig = viz.plot_outputfit(x, xhat, z=(x > 0).astype(int))
        assert len(fig.axes) == 1

    def test_inputoutputprob(self):
        rng = np.random.default_rng(6)
        N, T, M, K = 15, 40, 2, 3
        fig = viz.plot_inputoutputprob(
            rng.normal(size=T),
            rng.normal(size=(T, M)),
            rng.dirichlet(np.ones(K), size=(N, T)),
            rng.integers(0, K, size=(N, T)),
        )
        assert len(fig.axes) == M + 3

    def test_inputoutputprob_length_mismatch(self):
        rng = np.random.default_rng(7)
        with pytest.raises(ValueError):
            viz.plot_inputoutputprob(
                rng.normal(size=10),
                rng.normal(size=(10, 2)),
                rng.dirichlet(np.ones(2), size=(5, 12)),
                rng.integers(0, 2, size=(5, 12)),
            )

    def test_seqforecast(self):
        rng = np.random.default_rng(8)
        y = np.cumsum(rng.normal(size=50)) + 30
        point = y[-1] + np.arange(1, 6) * 0.1
        fig = viz.plot_seqforecast(y, np.stack([point - 1, point, point + 1]))
        assert len(fig.axes) == 1


class TestTayalPlots:
    def test_features(self, tick_data):
        price, size, _, zig = tick_data
        for which in ("actual", "extrema", "trend", "all"):
            fig = viz.plot_features(price, zig, which=which)
            assert len(fig.axes) == 2

    def test_topstate_hist(self, tick_data):
        price, _, _, zig = tick_data
        rng = np.random.default_rng(0)
        top = map_to_topstate(rng.integers(0, 4, size=len(zig)))
        leg_ret = np.diff(price[zig.end], prepend=price[zig.start[0]])
        fig = viz.plot_topstate_hist(leg_ret, top)
        assert len(fig.axes) == 2

    def test_topstate_seq_and_seqv(self, tick_data):
        price, _, _, zig = tick_data
        rng = np.random.default_rng(1)
        leg_top = map_to_topstate(rng.integers(0, 4, size=len(zig)))
        tick_top = expand_to_ticks(leg_top, zig, price.size)
        assert len(viz.plot_topstate_seq(price, tick_top).axes) == 1
        assert len(viz.plot_topstate_seqv(price, zig, leg_top).axes) == 2

    def test_topstate_features(self, tick_data):
        _, _, _, zig = tick_data
        rng = np.random.default_rng(2)
        leg_top = map_to_topstate(rng.integers(0, 4, size=len(zig)))
        fig = viz.plot_topstate_features(zig.feature, leg_top, L=18)
        assert len(fig.axes) == 1

    def test_topstate_trading(self, tick_data):
        price, _, _, zig = tick_data
        rng = np.random.default_rng(3)
        leg_top = map_to_topstate(rng.integers(0, 4, size=len(zig)))
        tick_top = expand_to_ticks(leg_top, zig, price.size)
        trades = {
            f"lag {lag}": topstate_trading(price, tick_top, lag=lag)
            for lag in (0, 1)
        }
        fig = viz.plot_topstate_trading(price, tick_top, trades)
        assert len(fig.axes) == 2


def test_compiled_report_builds(tmp_path, monkeypatch):
    """The single-file HTML report (analog of the reference's rendered
    main.html/main.pdf) builds from the committed docs with every page
    present and no unresolved local images."""
    import re
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "docs"))
    import build_report

    html = build_report.build()
    for fname, _ in build_report.PAGES:
        anchor = f'id="page-{fname.rsplit(".", 1)[0]}"'
        assert anchor in html, fname
    assert not re.findall(r'<img[^>]*src="(?!data:)[^"]*"', html)
    assert html.count("data:image") >= 10
