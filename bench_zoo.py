"""Benchmark zoo: the BASELINE.md config ladder beyond the north-star.

`bench.py` is the driver-facing benchmark (config #5, batched Tayal —
one JSON line). This script times the remaining reference workloads on
the chip, one JSON line per config (same schema), so speedups are
recorded across the whole model family:

  hmm      Gaussian HMM K=3, T=500 sim→fit        (config #1)
  iohmm    IOHMM-reg K=3, M=4, T=300 sim→fit       (config #2)
  hmix     IOHMM-hmix K=4, L=3 Hassan daily config (config #3)
  tayal    Tayal HHMM, single series               (config #4)
  jangmin  63-leaf Jangmin market tree, T=100      (the reference's
           "toy HHMM" sat at ≈25 min for a SMALLER 23-state version)
  hsmm     explicit-duration Gaussian HSMM K=2, Dmax=6, T=400 sim→fit
           on the K*Dmax count-down expansion (models/hsmm.py) — the
           duration-aware zoo member; baseline charged at the
           Gaussian-HMM budget class (the reference has no HSMM at
           all: its geometric-duration chain is the thing this config
           exists to beat)

Quality discipline (round 4, VERDICT r3 #6): a wall-clock speedup at
ESS(lp) 5 is not a fit. Every row is AUTO-RE-BUDGETED — samples double
until the run's own ESS(lp) >= --min-ess (default 50, the Stan-
comparable bar) or the cap is hit; the printed row is the PASSING run
(its real wall-clock, its real ESS), with the re-budget trail recorded.
Rows that still miss the bar at the cap carry an explicit
"quality_flag" and must not be quoted as headline speedups.

Baselines (BASELINE.md / reference log): the reference records ≈5 min
for an IOHMM-mix smaller than config #2/#3's shapes and ≈30 min for the
K=4 Hassan config; Gaussian-HMM fits share the ≈5-min budget class. We
charge the baseline column conservatively per config below. Single
fits on an accelerator are latency-bound, not throughput-bound — the
batched configs in `bench.py` are where the hardware pays off; these
numbers exist to show *every* reference workload still beats its CPU
wall-clock without batching.

Provenance (observability PR discipline): every row is stamped with
the same jax/jaxlib/device-kind fields and compact manifest stanza
`bench.py` emits, so zoo records are `scripts/bench_diff.py`-gateable
instead of permanently ungated. The row's own unit (``sec/fit``) is
latency-shaped and therefore never throughput-gated; each row is
accompanied by a ``<metric>_ess_rate`` record (``ess/sec`` — the
quality-normalized throughput BASELINE.md ranks by), which IS gated
between manifest-comparable rounds. The final (post-re-budget) draw
count is part of the workload digest: rows fitted at different budgets
are different workloads and must never gate against each other — the
exact r01-vs-r04 trap the manifest stanza exists to close.
"""

from __future__ import annotations

import argparse
import json
import sys
from time import perf_counter

import numpy as np

import jax
import jax.numpy as jnp

from hhmm_tpu.obs import manifest as obs_manifest
from hhmm_tpu.obs.telemetry import install_listeners, register_jit


def _time_fit(model, data, config, key, fused_traj=False):
    from hhmm_tpu.infer import ChEESConfig, GibbsConfig, sample_chees, sample_gibbs, sample_nuts
    from hhmm_tpu.infer.diagnostics import ess

    np_data = {k: np.asarray(v) for k, v in data.items()}
    data = {k: jnp.asarray(v) for k, v in data.items()}
    if isinstance(config, GibbsConfig):
        # default init does host-side work (k-means/bincount) — build it
        # outside the jit and pass it in
        init = jnp.stack(
            [
                model.init_unconstrained(k, np_data)
                for k in jax.random.split(jax.random.PRNGKey(7), config.num_chains)
            ]
        )

        def run(key):
            return sample_gibbs(model, data, key, config, init_q=init, jit=False)

    elif isinstance(config, ChEESConfig):
        # single posterior, C chains: plain per-posterior ChEES — the
        # cross-chain criterion replaces NUTS's per-transition trees
        from hhmm_tpu.batch import default_init

        theta0_b = default_init(
            model,
            {k: v[None] for k, v in np_data.items()},
            1,
            config.num_chains,
            jax.random.PRNGKey(7),
        )
        traj = None
        data_b = {k: v[None] for k, v in data.items()}
        if fused_traj:
            # whole-trajectory Pallas kernel (kernels/pallas_traj.py)
            # run as a B=1 batch — VERDICT r2 #4: the single-fit path
            # gets the same fused hot loop as the batched bench
            from hhmm_tpu.kernels.dispatch import make_tayal_trajectory

            try:
                traj = make_tayal_trajectory(data_b, cap=config.max_leapfrogs)
            except ValueError as e:  # non-TPU backend or T over VMEM
                print(f"# fused trajectory disabled: {e}", flush=True)
        if traj is not None:
            from hhmm_tpu.infer import make_lp_bc, sample_chees_batched

            lp_bc = make_lp_bc(model, data_b)
            probe = model.make_vg(data)

            def run(key):
                qs, stats = sample_chees_batched(
                    lp_bc, key, theta0_b, config, jit=False,
                    probe_vg=probe, trajectory_fn=traj,
                )
                # keep only the per-series stats _time_fit reads
                # (inv_mass has no leading batch axis)
                return qs[0], {
                    "diverging": stats["diverging"][0],
                    "logp": stats["logp"][0],
                }

        else:
            vg = model.make_vg(data)
            theta0 = theta0_b[0]

            def run(key):
                return sample_chees(None, key, theta0, config, jit=False, vg_fn=vg)

    else:
        theta0 = model.init_unconstrained(jax.random.PRNGKey(7), data)

        # NUTS runs as a 1-series vmapped batch: the semantically
        # identical UNBATCHED form (NUTS while_loop over the unbatched
        # Pallas vg) trips a reproducible TPU compile fault on the
        # current tunnel toolchain (3/3 attempts, round 4), while the
        # vmapped form — the same program every batched driver uses —
        # compiles and runs at the same per-fit cost (measured 3.81 s
        # vs the r3 record's 3.74 s for tayal)
        def run(key):
            def one(qi, ki):
                vg = model.make_vg(data)
                qs, stats = sample_nuts(None, ki, qi, config, jit=False, vg_fn=vg)
                # only the stats _time_fit reads: the full stats pytree
                # (energies, accept probs, ...) both bloats transfers
                # and has been implicated in the tunnel compile fault
                return qs, {"logp": stats["logp"], "diverging": stats["diverging"]}

            qs, stats = jax.vmap(one)(theta0[None], key[None])
            return qs[0], {k: v[0] for k, v in stats.items()}

    # registered entry point (check_guards invariant 5b): run manifests
    # attribute the zoo's compile counts like every other bench jit
    runj = register_jit("bench_zoo.run", jax.jit(run))
    jax.block_until_ready(runj(jax.random.PRNGKey(999)))  # compile
    # monotonic clock only (check_guards invariant 5a)
    t0 = perf_counter()
    _, stats = jax.block_until_ready(runj(key))
    dt = perf_counter() - t0
    div = float(np.asarray(stats["diverging"]).mean())
    lp = np.asarray(stats["logp"])
    ess_lp = float(ess(lp.reshape(-1, lp.shape[-1])))
    return dt, div, ess_lp


def bench_hmm(cfg):
    from hhmm_tpu.infer import GibbsConfig
    from hhmm_tpu.models import GaussianHMM, NIGPrior
    from hhmm_tpu.sim import hmm_sim, obsmodel_gaussian

    K, T = 3, 500
    A = np.array([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1], [0.05, 0.15, 0.8]])
    z, x = hmm_sim(
        jax.random.PRNGKey(0), T, A, np.ones(K) / K,
        obsmodel_gaussian(np.array([-2.0, 0.5, 3.0]), np.array([0.5, 0.8, 0.6])),
    )
    # Gibbs path: the NIG emission prior enables the conjugate block
    # (FFBS + joint NIG draws, models/gaussian_hmm.py)
    model = (
        GaussianHMM(K=K, nig_prior=NIGPrior(m0=0.0, kappa0=0.1, a0=2.0, b0=1.0))
        if isinstance(cfg, GibbsConfig)
        else GaussianHMM(K=K)
    )
    dt, div, ess_lp = _time_fit(model, {"x": x}, cfg, jax.random.PRNGKey(1))
    return "gaussian_hmm_fit", dt, div, ess_lp, 300.0  # ≈5-min CPU budget class


def bench_iohmm(cfg):
    from hhmm_tpu.models import IOHMMReg
    from hhmm_tpu.sim import iohmm_sim, obsmodel_reg

    K, M, T = 3, 4, 300
    rng = np.random.default_rng(0)
    u = np.column_stack([np.ones(T), rng.normal(size=(T, M - 1))])
    w = rng.normal(size=(K, M)) * 1.5
    b = rng.normal(size=(K, M))
    sim = iohmm_sim(jax.random.PRNGKey(0), u, w, obsmodel_reg(b, np.full(K, 0.4)))
    dt, div, ess_lp = _time_fit(
        IOHMMReg(K=K, M=M), {"u": sim["u"], "x": sim["x"]}, cfg, jax.random.PRNGKey(1)
    )
    return "iohmm_reg_fit", dt, div, ess_lp, 300.0


def bench_hmix(cfg):
    from hhmm_tpu.apps.hassan.data import make_dataset, simulate_ohlc
    from hhmm_tpu.apps.hassan.wf import DEFAULT_HYPERPARAMS
    from hhmm_tpu.models import IOHMMHMix

    ohlc = simulate_ohlc(np.random.default_rng(2), 160)
    ds = make_dataset(np.asarray(ohlc))
    model = IOHMMHMix(K=4, M=4, L=3, hyperparams=DEFAULT_HYPERPARAMS)
    dt, div, ess_lp = _time_fit(
        model, {"u": ds.u, "x": ds.x}, cfg, jax.random.PRNGKey(1)
    )
    return "iohmm_hmix_hassan_fit", dt, div, ess_lp, 1800.0  # reference: ≈30 min for K=4


def bench_tayal(cfg):
    from __graft_entry__ import _tayal_batch
    from hhmm_tpu.infer import GibbsConfig
    from hhmm_tpu.models import TayalHHMM

    # Gibbs needs the exact-HMM factorization (hard gate; identical on
    # strictly-alternating zig-zag signs)
    model = TayalHHMM(gate_mode="hard") if isinstance(cfg, GibbsConfig) else TayalHHMM()
    x, sign = _tayal_batch(1, 1024, seed=3)
    dt, div, ess_lp = _time_fit(
        model, {"x": x[0], "sign": sign[0]}, cfg, jax.random.PRNGKey(1),
        fused_traj=True,  # chees: whole-trajectory Pallas kernel
    )
    return "tayal_single_fit", dt, div, ess_lp, 120.0


def bench_jangmin(cfg):
    from hhmm_tpu.apps.jangmin import simulate_market
    from hhmm_tpu.hhmm.examples import jangmin2004_tree
    from hhmm_tpu.models import TreeHMM

    m = simulate_market(100, np.random.default_rng(0))
    model = TreeHMM(jangmin2004_tree(), semisup=True, gate_mode="hard", order_mu="none")
    data = {"x": m["x"], "g": m["regime"]}
    dt, div, ess_lp = _time_fit(model, data, cfg, jax.random.PRNGKey(1))
    # reference: ≈25 min for a 23-state toy at 100 obs / 200 samples;
    # this is the full 63-leaf tree — same baseline, conservatively
    return "jangmin_tree_fit", dt, div, ess_lp, 1500.0


def bench_hsmm(cfg):
    from hhmm_tpu.infer import GibbsConfig
    from hhmm_tpu.models import GaussianHSMM, NIGPrior
    from hhmm_tpu.sim import hsmm_sim, obsmodel_gaussian

    K, Dmax, T = 2, 6, 400
    # non-geometric dwell structure: peaked durations a geometric chain
    # cannot represent — the regime holds ~4-6 ticks, then flips
    A = np.array([[0.0, 1.0], [1.0, 0.0]])
    dur = np.array(
        [[0.02, 0.03, 0.15, 0.40, 0.30, 0.10],
         [0.02, 0.08, 0.30, 0.40, 0.15, 0.05]]
    )
    z, x = hsmm_sim(
        jax.random.PRNGKey(0), T, A, dur, np.ones(K) / K,
        obsmodel_gaussian(np.array([-0.8, 0.8]), np.array([0.7, 0.7])),
    )
    model = (
        GaussianHSMM(
            K=K, Dmax=Dmax,
            nig_prior=NIGPrior(m0=0.0, kappa0=0.1, a0=2.0, b0=1.0),
        )
        if isinstance(cfg, GibbsConfig)
        else GaussianHSMM(K=K, Dmax=Dmax)
    )
    dt, div, ess_lp = _time_fit(model, {"x": x}, cfg, jax.random.PRNGKey(1))
    return "gaussian_hsmm_fit", dt, div, ess_lp, 300.0  # HMM budget class


CONFIGS = {
    "hmm": bench_hmm,
    "iohmm": bench_iohmm,
    "hmix": bench_hmix,
    "tayal": bench_tayal,
    "jangmin": bench_jangmin,
    "hsmm": bench_hsmm,
}


def main() -> None:
    from hhmm_tpu.infer import SamplerConfig

    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", nargs="*", default=list(CONFIGS))
    ap.add_argument("--warmup", type=int, default=250)
    ap.add_argument("--samples", type=int, default=250)
    ap.add_argument("--max-treedepth", type=int, default=6)
    ap.add_argument(
        "--sampler",
        choices=["nuts", "chees", "gibbs"],
        default="nuts",
        help="nuts (default; Stan semantics); chees — per-posterior "
        "cross-chain adaptation (infer/chees.py), --chains >= 2; gibbs — "
        "blocked conjugate FFBS (conjugate configs: tayal, hmm, hsmm, and "
        "jangmin via the route-augmented tree sampler, hhmm/routes.py)",
    )
    ap.add_argument("--chains", type=int, default=None)
    ap.add_argument("--max-leapfrogs", type=int, default=32)
    ap.add_argument(
        "--min-ess",
        type=float,
        default=50.0,
        help="quality bar: rows re-budget (samples grow) until their "
        "own ESS(lp) reaches this; rows still below at --max-samples "
        "are flagged",
    )
    ap.add_argument("--max-samples", type=int, default=16_000)
    ap.add_argument(
        "--quick",
        action="store_true",
        help="smoke-test budgets (the bench.py convention): caps "
        "warmup/samples/max-samples and relaxes --min-ess so every "
        "config completes in seconds; rows are still stamped and the "
        "shrunk budgets land in the workload digest, so quick rows "
        "can never gate against full-budget rows",
    )
    ap.add_argument("--cpu", action="store_true", help="force the CPU backend")
    args = ap.parse_args()
    if args.quick:
        args.warmup = min(args.warmup, 40)
        args.samples = min(args.samples, 40)
        args.max_samples = min(args.max_samples, 160)
        args.min_ess = min(args.min_ess, 8.0)
    # compile telemetry before the first jit (the bench.py discipline):
    # the manifest stanzas stamped onto every row then carry the run's
    # real backend-compile counts instead of a dead listener
    install_listeners()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    if args.sampler == "gibbs":
        from hhmm_tpu.infer import GibbsConfig

        cfg = GibbsConfig(
            num_warmup=args.warmup,
            num_samples=args.samples,
            num_chains=args.chains or 1,
        )
    elif args.sampler == "chees":
        from hhmm_tpu.infer import ChEESConfig

        cfg = ChEESConfig(
            num_warmup=args.warmup,
            num_samples=args.samples,
            num_chains=args.chains or 4,
            max_leapfrogs=args.max_leapfrogs,
        )
    else:
        cfg = SamplerConfig(
            num_warmup=args.warmup,
            num_samples=args.samples,
            num_chains=args.chains or 1,
            max_treedepth=args.max_treedepth,
        )
    if args.sampler == "gibbs":
        bad = [
            c for c in args.configs if c not in ("tayal", "hmm", "jangmin", "hsmm")
        ]
        if bad:
            raise SystemExit(
                f"--sampler gibbs supports only the conjugate configs "
                f"(tayal, hmm, jangmin, hsmm); drop {bad} or use "
                f"--configs tayal hmm jangmin hsmm"
            )
    from dataclasses import replace as _replace

    rows = []
    for name in args.configs:
        samples = args.samples
        trail = []
        while True:
            cfg_n = _replace(cfg, num_samples=samples)
            metric, dt, div, ess_lp, baseline_s = CONFIGS[name](cfg_n)
            trail.append({"samples": samples, "ess_lp": round(ess_lp, 1)})
            if ess_lp >= args.min_ess or samples >= args.max_samples:
                break
            # ESS grows ~linearly in draws for a stationary chain:
            # jump straight toward the target with 1.5x headroom,
            # at least doubling
            factor = max(2.0, 1.5 * args.min_ess / max(ess_lp, 1e-3))
            samples = min(args.max_samples, int(samples * factor))
        # stamp + manifest stanza (obs/manifest.py): the bench.py record
        # discipline — host/stack identity on the record itself, and the
        # workload-digest comparability key bench_diff gates on. The
        # digest is computed from the ROW's measured workload (config
        # name, sampler, chains, budgets, FINAL re-budgeted draw count)
        # — not argparse state like output flags
        versions = obs_manifest.stack_versions()
        wl = {
            "config": name,
            "sampler": args.sampler,
            "chains": args.chains,
            "warmup": args.warmup,
            "samples": samples,
            "max_treedepth": args.max_treedepth,
            "max_leapfrogs": args.max_leapfrogs,
            "quick": args.quick,
            "cpu": args.cpu,
        }
        stanza = obs_manifest.manifest_stanza(
            config=vars(args), seed=7, workload_config=wl
        )
        stamp = {
            "jax_version": versions.get("jax"),
            "jaxlib_version": versions.get("jaxlib"),
            "device_kind": obs_manifest.device_info().get("device_kind"),
        }
        row = {
            "metric": metric,
            "value": round(dt, 3),
            "unit": "sec/fit",
            "vs_baseline": round(baseline_s / dt, 2),
            "divergence_rate": round(div, 4),
            "ess_lp": round(ess_lp, 1),
            "ess_lp_per_sec": round(ess_lp / dt, 1),
            "samples": samples,
            **stamp,
            "manifest": stanza,
        }
        if len(trail) > 1:
            row["rebudget_trail"] = trail
        if ess_lp < args.min_ess:
            row["quality_flag"] = f"ESS_LP_BELOW_{args.min_ess}"
        # print each row AS IT COMPLETES: a crash in a later config
        # (device fault, OOM) must not lose the finished rows
        print(json.dumps(row), flush=True)
        # the GATEABLE companion: quality-normalized throughput in a
        # /sec unit, same manifest identity — bench_diff binds on it
        if "quality_flag" not in row:
            print(
                json.dumps(
                    {
                        "metric": f"{metric}_ess_rate",
                        "value": row["ess_lp_per_sec"],
                        "unit": "ess/sec",
                        "samples": samples,
                        **stamp,
                        "manifest": stanza,
                    }
                ),
                flush=True,
            )
        rows.append(row)
    # ESS/sec ranking of the finished ladder — the quality-normalized
    # ordering (BASELINE.md "ESS/sec vs Stan NUTS baseline")
    ranked = sorted(rows, key=lambda r: -r["ess_lp_per_sec"])
    print(
        "# ess/sec ranking: "
        + " > ".join(f"{r['metric']}({r['ess_lp_per_sec']})" for r in ranked),
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
