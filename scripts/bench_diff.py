#!/usr/bin/env python
"""Bench-regression gate over the ``BENCH_*.json`` trajectory.

Loads every round's bench record, matches records by metric key, prints
a per-metric delta table, and exits non-zero when a **comparable** pair
regresses by more than the threshold (default 10% throughput).

Comparability is the whole point. The trajectory spans different hosts,
backends, and sampler budgets — r01's 1295 series/s and r04's 41
series/s differ because the gibbs draw budget grew 64× for the ESS
gate, not because the code got slower. A naive latest-vs-previous gate
over raw values would be permanently red (or, tuned loose enough to
pass, permanently useless). So the gate only binds between records that
carry a ``manifest`` stanza (`hhmm_tpu/obs/manifest.py`, emitted by
`bench.py` since the observability PR) with matching

    (metric, workload_digest, backend, device_kind, jax_version,
     trace_enabled)

— same workload on the same stack under the same measurement regime
(a traced run pays sync boundaries and span bookkeeping the untraced
run doesn't; comparing across that flag would gate observability
overhead as a perf regression). Everything else still appears in
the delta table, marked ungated, with the reason. Pre-manifest records
(r01–r05) are therefore visible but never gate: exactly the "not
comparable across hosts without out-of-band knowledge" gap the stamps
close going forward.

Further gate rules:

- only higher-is-better metrics gate (unit ends in ``/sec``; latency
  and counter fields ride along in the table only);
- a crashed round (rc != 0, no parsed record) is reported and skipped —
  crash-robustness is `bench.py`'s own job (`ensure_backend`), not this
  gate's;
- a degraded record (``degraded_cpu_smoke`` / ``backend_fallback``)
  never gates in either direction — a CPU fallback run regressing
  against a TPU run is a backend change, not a perf change;
- **SLO attainment gates like throughput**: a record whose manifest
  stanza carries an ``slo`` verdict (`bench.py --serve` /
  ``--serve-storm`` embed the `serve/metrics.py` ``evaluate_slo``
  result) fails the gate when the previous comparable record ATTAINED
  its SLOs and this one does not — the serving-objective analog of a
  throughput regression. A first record that is already unmet is
  reported (never silently green) but has no baseline to regress from,
  so it does not gate.
- **Resilience gates the same way**: a record whose manifest stanza
  carries a ``storm`` verdict (`bench.py --serve-storm`) fails the
  gate when a comparable clean baseline (zero escaped faults) is
  followed by a record with ``faults_escaped > 0`` — an injected fault
  leaking out as an exception is a survival regression even if the
  bench somehow exited 0.
- **Scheduler fairness gates within the record**: a ``storm`` stanza
  carrying the FIFO-vs-DRR duel fields
  (``fairness.fifo_p99_spread_ms`` / ``fairness.drr_p99_spread_ms``)
  fails the gate unless DRR's skewed-probe spread sits STRICTLY below
  the FIFO baseline's — the duel ships its own baseline arm, so no
  prior record is needed. Warm page-in parity
  (``warm_page_in.parity``) gates like the SLO: a comparable baseline
  that reproduced the never-evicted stream followed by a record that
  does not is a replay-correctness regression.
- **Maintenance gates like resilience**: a record whose manifest
  stanza carries a ``maint`` stanza (`bench.py --maint`,
  `hhmm_tpu/maint/`) fails the gate when a comparable baseline that
  PROMOTED (``promotions > 0``) is followed by a record with zero
  promotions — the drift→refit→shadow→promote ladder going dark on the
  same workload is a closed-loop regression even if the bench's own
  gates were loosened. A first record with zero promotions is reported
  but has no promoting baseline, so it does not gate.
- **Adaptation gates like resilience**: a record whose manifest stanza
  carries an ``adapt`` stanza (`bench.py --adapt`, `hhmm_tpu/adapt/`)
  fails the gate when a comparable baseline that TRACKED
  (``tracking_advantage`` true — the reweighted/rejuvenated mixture
  beat the uniform-stale arm post-shift) is followed by one that does
  not, or when a baseline with zero ``floor_breaches`` is followed by
  a record whose tracked series sit below the ESS floor — either way
  the cheap rungs of the reweight→rejuvenate→refit ladder stopped
  carrying their load. A first record without a tracking/clean
  baseline is reported ungated.
- **Request-plane health gates inverted too**: a record whose manifest
  stanza carries a ``request`` stanza (`hhmm_tpu/obs/request.py`,
  embedded by ``bench.py --serve`` / ``--serve-storm``) fails the gate
  when its fairness p99 spread (``fairness.p99_spread_ms``) or overall
  queue share (``overall.queue_share``) GREW by more than the
  threshold against the previous comparable record — spread growth is
  tenant starvation creeping in, queue-share growth is latency
  migrating out of the device and into the pending queue; both are
  lower-is-better, so the throughput threshold applies with the sign
  flipped. A zero/absent baseline cannot gate, and neither can a
  baseline below the noise floor (spread < 5 ms / queue share < 0.05):
  unlike the large stable throughput values this gate mirrors, a
  near-zero spread is cross-tenant scheduling jitter, and relative
  growth on jitter would false-fail CI (both cases report as the
  request-plane baseline instead).
- **The async-pipeline duel gates within the record**: a ``pipeline``
  stanza carrying the sync-vs-async overlap duel fields
  (``sync_queue_share`` / ``async_queue_share``, from ``bench.py
  --pipeline``, `hhmm_tpu/pipeline/`) fails the gate unless the async
  arm's queue share sits STRICTLY below the sync baseline's with zero
  parity mismatches — like the FIFO-vs-DRR duel, the stanza ships its
  own baseline arm, so no prior record is needed. Equality means the
  double-buffered dispatch/harvest split hid nothing; a parity
  mismatch means it hid latency by serving different posteriors.
- **Kernel device time gates inverted**: a record whose manifest
  stanza carries a ``kernel_costs`` table (`bench.py
  --profile-kernels`, `hhmm_tpu/obs/profile.py`) fails the gate when
  a row's measured ``p50_ms`` GREW by more than the threshold against
  the same row (kernel/branch/K/T/B/dtype) of the previous comparable
  record — device time is lower-is-better, so the throughput
  threshold applies with the sign flipped. Rows without a measured
  p50 (unmeasured) ride along ungated, and rows whose XLA cost
  analysis came back empty are reported as timing-only (they still
  gate on time — only the roofline column is blind).

Exit codes: 0 clean (or nothing comparable), 1 regression, 2 usage/IO
error. No jax import — this runs in CI guards and pre-push hooks.

Usage::

    python scripts/bench_diff.py                 # repo BENCH_*.json
    python scripts/bench_diff.py --dir /path --threshold 5
    python scripts/bench_diff.py --metric tayal_serve_tick_throughput
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

GATED_UNIT_RE = re.compile(r"/s(ec)?$")


def _last_json_line(text: str) -> Optional[Dict[str, Any]]:
    """Fallback extraction of a metric record from a round's captured
    tail when the driver's own ``parsed`` stanza is null."""
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            return rec
    return None


def load_rounds(paths: List[str]) -> List[Dict[str, Any]]:
    """One entry per bench round file, ordered by round number ``n``:
    ``{n, file, rc, record}`` where ``record`` is the metric JSON (or
    None for a crashed round). Files may be either the driver wrapper
    shape (``{"n", "rc", "tail", "parsed"}``) or a bare metric record
    (fixture / future direct-emission form)."""
    rounds = []
    for path in paths:
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"# bench_diff: skipping unreadable {path} ({e})", file=sys.stderr)
            continue
        if "metric" in d:  # bare record
            m = re.search(r"(\d+)", os.path.basename(path))
            rounds.append(
                {"n": int(m.group(1)) if m else 0, "file": path, "rc": 0, "record": d}
            )
            continue
        rec = d.get("parsed")
        if rec is None and isinstance(d.get("tail"), str):
            rec = _last_json_line(d["tail"])
        rounds.append(
            {
                "n": int(d.get("n", 0)),
                "file": path,
                "rc": int(d.get("rc", 0)),
                "record": rec if isinstance(rec, dict) else None,
            }
        )
    rounds.sort(key=lambda r: (r["n"], r["file"]))
    return rounds


def comparability_key(rec: Dict[str, Any]) -> Tuple[Optional[Tuple], Optional[str]]:
    """``(key, why_not)``: the full comparability key for a record, or
    ``(None, reason)`` when it cannot gate."""
    unit = str(rec.get("unit", ""))
    if not GATED_UNIT_RE.search(unit):
        return None, f"unit {unit!r} not a throughput"
    if rec.get("degraded_cpu_smoke") or rec.get("backend_fallback"):
        return None, "degraded/fallback run"
    man = rec.get("manifest")
    if not isinstance(man, dict):
        return None, "no manifest stanza (pre-observability record)"
    parts = {
        "workload_digest": man.get("workload_digest"),
        "backend": rec.get("backend") or man.get("backend"),
        "device_kind": man.get("device_kind"),
        "jax": (man.get("versions") or {}).get("jax"),
    }
    missing = [k for k, v in parts.items() if not v]
    if missing:
        return None, f"manifest missing {missing}"
    return (
        rec["metric"],
        parts["workload_digest"],
        parts["backend"],
        parts["device_kind"],
        parts["jax"],
        # measurement regime: traced runs carry sync + span overhead and
        # must only ever compare against other traced runs
        bool(man.get("trace_enabled")),
    ), None


def diff(
    rounds: List[Dict[str, Any]],
    threshold_pct: float,
    metric_filter: Optional[str] = None,
) -> Tuple[List[Dict[str, Any]], int]:
    """Build the delta table and count gate failures."""
    rows: List[Dict[str, Any]] = []
    last_by_metric: Dict[str, Dict[str, Any]] = {}
    last_by_key: Dict[Tuple, Dict[str, Any]] = {}
    last_slo_by_key: Dict[Tuple, bool] = {}
    last_escaped_by_key: Dict[Tuple, int] = {}
    last_parity_by_key: Dict[Tuple, bool] = {}
    last_promotions_by_key: Dict[Tuple, int] = {}
    last_tracking_by_key: Dict[Tuple, bool] = {}
    last_breaches_by_key: Dict[Tuple, int] = {}
    last_costs_by_key: Dict[Tuple, Dict[str, float]] = {}
    last_request_by_key: Dict[Tuple, Dict[str, Optional[float]]] = {}
    last_transfer_by_key: Dict[Tuple, Dict[str, float]] = {}
    failures = 0
    for rnd in rounds:
        rec = rnd["record"]
        if rec is None:
            if metric_filter:
                # a crashed round has no metric: it belongs to the full
                # report, not to a single-metric table
                continue
            rows.append(
                {
                    "n": rnd["n"],
                    "metric": "-",
                    "value": None,
                    "unit": "",
                    "delta_pct": None,
                    "gated": False,
                    "status": f"CRASHED (rc={rnd['rc']})",
                }
            )
            continue
        metric = str(rec.get("metric", "?"))
        if metric_filter and metric != metric_filter:
            continue
        value = rec.get("value")
        row: Dict[str, Any] = {
            "n": rnd["n"],
            "metric": metric,
            "value": value,
            "unit": str(rec.get("unit", "")),
            "delta_pct": None,
            "gated": False,
            "status": "",
        }
        prev_any = last_by_metric.get(metric)
        if (
            prev_any is not None
            and isinstance(value, (int, float))
            and isinstance(prev_any.get("value"), (int, float))
            and prev_any["value"]
        ):
            row["delta_pct"] = 100.0 * (value - prev_any["value"]) / prev_any["value"]
        key, why_not = comparability_key(rec)
        if key is None:
            row["status"] = f"ungated: {why_not}"
        else:
            prev = last_by_key.get(key)
            if prev is None:
                row["status"] = "baseline for its workload/stack key"
            elif not isinstance(value, (int, float)):
                row["status"] = "ungated: non-numeric value"
            elif not prev["value"]:
                row["status"] = f"ungated: zero baseline (round {prev['n']})"
            else:
                gated_delta = 100.0 * (value - prev["value"]) / prev["value"]
                row["gated"] = True
                row["delta_pct"] = gated_delta
                if gated_delta < -threshold_pct:
                    failures += 1
                    row["status"] = (
                        f"REGRESSION: {gated_delta:+.1f}% vs round {prev['n']} "
                        f"(threshold -{threshold_pct:g}%)"
                    )
                else:
                    row["status"] = f"ok vs round {prev['n']}"
            if isinstance(value, (int, float)):
                last_by_key[key] = {"n": rnd["n"], "value": value}
            # SLO attainment rides the same comparability key: an
            # attained -> unmet transition between comparable records
            # is a serving regression, gated exactly like throughput
            slo = (rec.get("manifest") or {}).get("slo")
            if isinstance(slo, dict) and "attained" in slo:
                attained = bool(slo.get("attained"))
                prev_attained = last_slo_by_key.get(key)
                if prev_attained is True and not attained:
                    failures += 1
                    row["gated"] = True
                    unmet = sorted(
                        k
                        for k, c in (slo.get("checks") or {}).items()
                        if isinstance(c, dict) and not c.get("ok")
                    )
                    row["status"] += (
                        f"; SLO REGRESSION: attained -> unmet ({', '.join(unmet)})"
                    )
                elif not attained:
                    row["status"] += "; SLO unmet (no attained baseline)"
                else:
                    row["status"] += "; SLO attained"
                last_slo_by_key[key] = attained
            # resilience rides the same key: a clean (zero-escape) storm
            # baseline followed by escaped faults is a survival
            # regression, gated like an attained -> unmet SLO transition
            storm = (rec.get("manifest") or {}).get("storm")
            if isinstance(storm, dict) and "faults_escaped" in storm:
                try:
                    esc = int(storm.get("faults_escaped") or 0)
                except (TypeError, ValueError):
                    esc = -1  # malformed: visible, never a clean baseline
                prev_esc = last_escaped_by_key.get(key)
                if prev_esc == 0 and esc != 0:
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        f"; RESILIENCE REGRESSION: {esc} escaped fault(s) "
                        "(baseline was clean)"
                    )
                elif esc != 0:
                    row["status"] += (
                        f"; {esc} escaped fault(s) (no clean baseline)"
                    )
                else:
                    row["status"] += "; faults contained"
                last_escaped_by_key[key] = esc
            if isinstance(storm, dict):
                # the scheduler-fairness duel rides the storm stanza:
                # a record carrying the FIFO-vs-DRR probe fields must
                # show DRR strictly below the FIFO baseline — equality
                # means the fair order bought nothing, inversion means
                # it made starvation WORSE (gated within the record:
                # the duel ships its own baseline arm)
                duel = storm.get("fairness")
                if isinstance(duel, dict) and "drr_p99_spread_ms" in duel:
                    fifo_ms = duel.get("fifo_p99_spread_ms")
                    drr_ms = duel.get("drr_p99_spread_ms")
                    if (
                        not isinstance(fifo_ms, (int, float))
                        or not isinstance(drr_ms, (int, float))
                        or drr_ms >= fifo_ms
                    ):
                        failures += 1
                        row["gated"] = True
                        row["status"] += (
                            "; FAIRNESS REGRESSION: DRR spread not "
                            f"strictly below FIFO (fifo={fifo_ms} ms, "
                            f"drr={drr_ms} ms)"
                        )
                    else:
                        row["status"] += (
                            f"; fair order holds (fifo={fifo_ms:g} ms "
                            f"-> drr={drr_ms:g} ms)"
                        )
                # warm page-in parity is gated like the SLO: a record
                # whose comparable baseline reproduced the
                # never-evicted stream, then stopped, silently serves
                # wrong posteriors after every eviction
                wpi = storm.get("warm_page_in")
                if isinstance(wpi, dict) and "parity" in wpi:
                    parity = bool(wpi.get("parity"))
                    prev_parity = last_parity_by_key.get(key)
                    if prev_parity and not parity:
                        failures += 1
                        row["gated"] = True
                        row["status"] += (
                            "; WARM PAGE-IN REGRESSION: replay parity "
                            "lost (baseline matched the never-evicted "
                            "stream)"
                        )
                    elif not parity:
                        row["status"] += (
                            "; warm page-in parity unmet (no matching "
                            "baseline)"
                        )
                    else:
                        row["status"] += "; warm page-in parity"
                    last_parity_by_key[key] = parity
            # the maintenance plane rides the same key, gated like the
            # resilience gate: a comparable record that PROMOTED
            # (promotions > 0) followed by one that could not close the
            # loop at all (promotions == 0) is a maintenance regression
            # — the drift->refit->shadow->promote ladder went dark
            maint = (rec.get("manifest") or {}).get("maint")
            if isinstance(maint, dict) and "promotions" in maint:
                try:
                    promos = int(maint.get("promotions") or 0)
                except (TypeError, ValueError):
                    promos = -1  # malformed: visible, never a baseline
                prev_promos = last_promotions_by_key.get(key)
                if prev_promos is not None and prev_promos > 0 and promos == 0:
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        "; MAINTENANCE REGRESSION: 0 promotions "
                        f"(baseline round promoted {prev_promos})"
                    )
                elif promos == 0:
                    row["status"] += (
                        "; no promotions (no promoting baseline)"
                    )
                else:
                    row["status"] += f"; maint promotions {promos}"
                last_promotions_by_key[key] = promos
            # the adaptation plane rides the same key, gated like the
            # resilience gate on two observables: the tracking verdict
            # (weighted/rejuvenated arm beat uniform-stale post-shift)
            # and ESS-floor breaches (tracked series whose weight
            # cloud degenerated without a rejuvenation catching it)
            adapt = (rec.get("manifest") or {}).get("adapt")
            if isinstance(adapt, dict) and "tracking_advantage" in adapt:
                tracking = bool(adapt.get("tracking_advantage"))
                prev_tracking = last_tracking_by_key.get(key)
                if prev_tracking is True and not tracking:
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        "; ADAPTATION REGRESSION: tracking advantage "
                        "lost (baseline beat the uniform-stale arm)"
                    )
                elif not tracking:
                    row["status"] += (
                        "; not tracking (no tracking baseline)"
                    )
                else:
                    row["status"] += "; adaptation tracking"
                last_tracking_by_key[key] = tracking
            if isinstance(adapt, dict) and "floor_breaches" in adapt:
                try:
                    breaches = int(adapt.get("floor_breaches") or 0)
                except (TypeError, ValueError):
                    breaches = -1  # malformed: visible, never a baseline
                prev_breaches = last_breaches_by_key.get(key)
                if prev_breaches == 0 and breaches != 0:
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        f"; ESS-FLOOR REGRESSION: {breaches} series "
                        "below the floor (baseline was clean)"
                    )
                elif breaches != 0:
                    row["status"] += (
                        f"; {breaches} below ESS floor (no clean baseline)"
                    )
                else:
                    row["status"] += "; ESS above floor"
                last_breaches_by_key[key] = breaches
            # the request plane rides the same key, gated INVERTED
            # (lower is better): fairness-spread growth is tenant
            # starvation creeping in, queue-share growth is latency
            # migrating into the pending queue (obs/request.py)
            req = (rec.get("manifest") or {}).get("request")
            if isinstance(req, dict):
                cur: Dict[str, Optional[float]] = {}
                # (observable, noise floor a baseline must clear to
                # gate): relative growth on a jitter-scale baseline
                # is not a regression signal
                floors = {"fairness-spread": 5.0, "queue-share": 0.05}
                for label, obs in (
                    (
                        "fairness-spread",
                        (req.get("fairness") or {}).get("p99_spread_ms"),
                    ),
                    (
                        "queue-share",
                        (req.get("overall") or {}).get("queue_share"),
                    ),
                ):
                    cur[label] = (
                        float(obs) if isinstance(obs, (int, float)) else None
                    )
                prev_req = last_request_by_key.get(key) or {}
                regressions = []
                n_req_gated = 0
                for label, v in cur.items():
                    pv = prev_req.get(label)
                    if v is None or not pv or pv < floors[label]:
                        continue  # unmeasured / noise-floor baseline
                    n_req_gated += 1
                    delta = 100.0 * (v - pv) / pv
                    if delta > threshold_pct:
                        regressions.append(f"{label} {delta:+.1f}%")
                if regressions:
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        "; REQUEST-PLANE REGRESSION: "
                        + ", ".join(regressions)
                        + f" (threshold +{threshold_pct:g}%)"
                    )
                elif n_req_gated:
                    row["status"] += (
                        f"; request plane ok ({n_req_gated} observable(s))"
                    )
                elif any(v is not None for v in cur.values()):
                    row["status"] += "; request-plane baseline"
                if any(v is not None for v in cur.values()):
                    # merge per label: a record missing ONE observable
                    # (e.g. a spread that was None this round) must not
                    # erase the other's measured baseline — the next
                    # measured value still gates against the last
                    # measured one
                    merged = dict(prev_req)
                    merged.update(
                        {l: v for l, v in cur.items() if v is not None}
                    )
                    last_request_by_key[key] = merged
            # the async-pipeline duel gates within the record, like the
            # FIFO-vs-DRR duel: the stanza ships its own sync baseline
            # arm, so the async arm's queue share must sit strictly
            # below it (equality = the overlap bought nothing) and the
            # posterior stream must match bitwise (a mismatch = it hid
            # latency by serving different answers)
            pipe = (rec.get("manifest") or {}).get("pipeline")
            if isinstance(pipe, dict) and "async_queue_share" in pipe:
                sync_q = pipe.get("sync_queue_share")
                async_q = pipe.get("async_queue_share")
                try:
                    mismatches = int(pipe.get("parity_mismatches") or 0)
                except (TypeError, ValueError):
                    mismatches = -1  # malformed: visible, never clean
                if (
                    not isinstance(sync_q, (int, float))
                    or not isinstance(async_q, (int, float))
                    or async_q >= sync_q
                ):
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        "; PIPELINE REGRESSION: async queue share not "
                        f"strictly below sync (sync={sync_q}, "
                        f"async={async_q})"
                    )
                elif mismatches != 0:
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        f"; PIPELINE REGRESSION: {mismatches} parity "
                        "mismatch(es) between the sync and async arms"
                    )
                else:
                    row["status"] += (
                        f"; pipeline overlap holds (queue {sync_q:g} "
                        f"-> {async_q:g})"
                    )
            # the carry-residency duel gates within the record the same
            # way: the stanza ships its own staged baseline arm, so the
            # resident arm must transfer STRICTLY fewer h2d bytes
            # (equality = the banks bought nothing) with bitwise
            # response parity (a byte win that changes answers is a
            # correctness bug wearing a perf hat)
            carry = (rec.get("manifest") or {}).get("carry")
            if isinstance(carry, dict) and "resident_h2d_bytes" in carry:
                staged_b = carry.get("staged_h2d_bytes")
                res_b = carry.get("resident_h2d_bytes")
                try:
                    mismatches = int(carry.get("parity_mismatches") or 0)
                except (TypeError, ValueError):
                    mismatches = -1  # malformed: visible, never clean
                if (
                    not isinstance(staged_b, (int, float))
                    or not isinstance(res_b, (int, float))
                    or res_b >= staged_b
                ):
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        "; CARRY REGRESSION: resident h2d bytes not "
                        f"strictly below staged (staged={staged_b}, "
                        f"resident={res_b})"
                    )
                elif mismatches != 0:
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        f"; CARRY REGRESSION: {mismatches} parity "
                        "mismatch(es) between the staged and resident arms"
                    )
                else:
                    row["status"] += (
                        f"; carry residency holds (h2d {staged_b:g} "
                        f"-> {res_b:g})"
                    )
                # transferred bytes per tick ride the same key, gated
                # INVERTED against prior comparable records: growth in
                # the resident arm's per-tick h2d/d2h past the
                # threshold means carry bytes crept back into the
                # per-flush transfer (e.g. a bank-hit path lost)
                prev_tx = last_transfer_by_key.get(key) or {}
                cur_tx: Dict[str, float] = {}
                tx_regr = []
                n_tx_gated = 0
                for label in (
                    "resident_h2d_bytes_per_tick",
                    "resident_d2h_bytes_per_tick",
                ):
                    v = carry.get(label)
                    if not isinstance(v, (int, float)) or v <= 0:
                        continue
                    cur_tx[label] = float(v)
                    pv = prev_tx.get(label)
                    if pv:
                        n_tx_gated += 1
                        delta = 100.0 * (v - pv) / pv
                        if delta > threshold_pct:
                            tx_regr.append(f"{label} {delta:+.1f}%")
                if tx_regr:
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        "; TRANSFER REGRESSION: "
                        + ", ".join(tx_regr)
                        + f" (threshold +{threshold_pct:g}%)"
                    )
                elif n_tx_gated:
                    row["status"] += (
                        f"; transfer bytes ok ({n_tx_gated} observable(s))"
                    )
                elif cur_tx:
                    row["status"] += "; transfer-bytes baseline"
                if cur_tx:
                    last_transfer_by_key[key] = cur_tx
            # kernel device time rides the same key, gated INVERTED:
            # a measured row whose p50 grew past the threshold against
            # the previous comparable record's same row is a device-
            # time regression (obs/profile.py cost plane)
            kc = (rec.get("manifest") or {}).get("kernel_costs")
            if isinstance(kc, dict) and isinstance(kc.get("rows"), list):
                prev_rows = last_costs_by_key.get(key) or {}
                cur_rows: Dict[str, float] = {}
                regressions = []
                n_gated_rows = n_unmeasured = n_timing_only = 0
                for kr in kc["rows"]:
                    if not isinstance(kr, dict):
                        continue
                    rk = "|".join(
                        str(kr.get(f))
                        for f in ("kernel", "branch", "K", "T", "B", "dtype")
                    )
                    p50 = kr.get("p50_ms")
                    if not isinstance(p50, (int, float)) or p50 <= 0:
                        n_unmeasured += 1
                        continue
                    if kr.get("timing_only"):
                        n_timing_only += 1
                    cur_rows[rk] = float(p50)
                    pv = prev_rows.get(rk)
                    if pv:
                        n_gated_rows += 1
                        delta = 100.0 * (p50 - pv) / pv
                        if delta > threshold_pct:
                            regressions.append(f"{rk} {delta:+.1f}%")
                if regressions:
                    failures += 1
                    row["gated"] = True
                    row["status"] += (
                        "; DEVICE-TIME REGRESSION: "
                        + ", ".join(regressions)
                        + f" (threshold +{threshold_pct:g}%)"
                    )
                elif n_gated_rows:
                    row["status"] += f"; kernel costs ok ({n_gated_rows} row(s))"
                elif cur_rows:
                    row["status"] += (
                        f"; kernel-cost baseline ({len(cur_rows)} row(s))"
                    )
                if n_unmeasured:
                    row["status"] += (
                        f"; {n_unmeasured} unmeasured kernel row(s) ungated"
                    )
                if n_timing_only:
                    row["status"] += (
                        f"; {n_timing_only} timing-only kernel row(s)"
                    )
                if cur_rows:
                    last_costs_by_key[key] = cur_rows
        if isinstance(value, (int, float)):
            last_by_metric[metric] = {"n": rnd["n"], "value": value}
        rows.append(row)
    return rows, failures


def print_table(rows: List[Dict[str, Any]], out=sys.stdout) -> None:
    headers = ("round", "metric", "value", "unit", "Δ%", "gate", "status")
    cells = [
        (
            f"r{r['n']:02d}",
            r["metric"],
            "-"
            if r["value"] is None
            else f"{r['value']:g}"
            if isinstance(r["value"], (int, float))
            else str(r["value"]),
            r["unit"],
            "-" if r["delta_pct"] is None else f"{r['delta_pct']:+.1f}",
            "*" if r["gated"] else "",
            r["status"],
        )
        for r in rows
    ]
    widths = [
        max(len(headers[i]), *(len(c[i]) for c in cells)) if cells else len(headers[i])
        for i in range(len(headers))
    ]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    print(fmt.format(*headers), file=out)
    print(fmt.format(*("-" * w for w in widths)), file=out)
    for c in cells:
        print(fmt.format(*c), file=out)


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--dir",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the bench records (default: repo root)",
    )
    ap.add_argument(
        "--glob", default="BENCH_*.json", help="record filename pattern"
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=10.0,
        metavar="PCT",
        help="max tolerated throughput regression between comparable "
        "records, in percent (default 10)",
    )
    ap.add_argument("--metric", default=None, help="gate only this metric key")
    args = ap.parse_args(argv[1:])

    paths = sorted(glob.glob(os.path.join(args.dir, args.glob)))
    if not paths:
        print(f"bench_diff: no records match {args.glob} under {args.dir}")
        return 2
    rounds = load_rounds(paths)
    if not rounds:
        print("bench_diff: no readable records")
        return 2
    rows, failures = diff(rounds, args.threshold, args.metric)
    print_table(rows)
    n_gated = sum(r["gated"] for r in rows)
    print(
        f"\nbench_diff: {len(rows)} record(s), {n_gated} gated pair "
        f"comparison(s), {failures} regression(s) beyond "
        f"{args.threshold:g}%"
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
