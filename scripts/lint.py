#!/usr/bin/env python
"""Pre-commit lint entry point — `hhmm_tpu.analysis` over the full
default scan set.

Exactly `python -m hhmm_tpu.analysis` with the repo root pinned (so it
works from any cwd and from a `.git/hooks/pre-commit` one-liner), plus
`--changed` to scan only files the working tree touches::

    python scripts/lint.py                 # full scan, text report
    python scripts/lint.py --changed       # staged+unstaged .py files only
    python scripts/lint.py --format json   # machine-readable
    make lint                              # Makefile spelling

Exit codes are the analyzer's: 0 clean, 1 findings, 2 config error.
Pure `ast` — no jax import, safe on any host.
"""

from __future__ import annotations

import pathlib
import subprocess
import sys
from typing import List

_REPO = pathlib.Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from hhmm_tpu.analysis.__main__ import main as analysis_main  # noqa: E402


def _changed_py_files() -> List[str]:
    """Tracked .py files the working tree modifies (staged + unstaged)
    plus untracked ones — the pre-commit scan set."""
    out = subprocess.run(
        ["git", "-C", str(_REPO), "status", "--porcelain"],
        capture_output=True,
        text=True,
        check=True,
    ).stdout
    files = []
    for line in out.splitlines():
        path = line[3:].split(" -> ")[-1].strip().strip('"')
        if path.endswith(".py") and (_REPO / path).is_file():
            files.append(path)
    return files


def main(argv: List[str]) -> int:
    args = list(argv[1:])
    if "--changed" in args:
        args.remove("--changed")
        changed = _changed_py_files()
        if not changed:
            print("lint: no changed .py files")
            return 0
        args.extend(changed)
    return analysis_main(["lint", "--root", str(_REPO), *args])


if __name__ == "__main__":
    sys.exit(main(sys.argv))
