#!/usr/bin/env python
"""Pre-commit lint entry point — `hhmm_tpu.analysis` over the full
default scan set.

Exactly `python -m hhmm_tpu.analysis` with the repo root pinned (so it
works from any cwd and from a `.git/hooks/pre-commit` one-liner), plus
`--changed` to scan only files the working tree touches::

    python scripts/lint.py                 # full scan, text report
    python scripts/lint.py --changed       # staged+unstaged .py files only
    python scripts/lint.py --format json   # machine-readable
    make lint                              # full scan + findings ratchet

Exit codes are the analyzer's: 0 clean, 1 findings, 2 config error.
Pure `ast` — no jax import, safe on any host.

`--changed` reads `git diff --name-status HEAD` (staged + unstaged in
one view) plus untracked files, so renames contribute their NEW path
and deletions contribute nothing — a renamed or deleted file must
never reach the engine as a dead path. `--repo DIR` overrides the
repo root (the tmp-repo regression test uses it).
"""

from __future__ import annotations

import pathlib
import subprocess
import sys
from typing import List

_REPO = pathlib.Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from hhmm_tpu.analysis.__main__ import main as analysis_main  # noqa: E402


def _changed_py_files(repo: pathlib.Path) -> List[str]:
    """Tracked .py files the working tree modifies relative to HEAD
    (staged + unstaged) plus untracked ones — the pre-commit scan set.

    `git diff --name-status HEAD` one-lines each change as
    `<status>\\t<path>` — or `R<score>\\t<old>\\t<new>` for renames and
    `C<score>\\t<src>\\t<dst>` for copies, where only the LAST path
    exists in the working tree. `D` (deleted) entries are dropped
    entirely; anything that no longer exists on disk (e.g. deleted
    after staging) is dropped too."""
    files: List[str] = []

    def add(path: str) -> None:
        path = path.strip().strip('"')
        if path.endswith(".py") and (repo / path).is_file():
            files.append(path)

    diff = subprocess.run(
        ["git", "-C", str(repo), "diff", "--name-status", "HEAD"],
        capture_output=True,
        text=True,
        check=True,
    ).stdout
    for line in diff.splitlines():
        parts = line.split("\t")
        if len(parts) < 2:
            continue
        status = parts[0]
        if status.startswith("D"):
            continue  # deleted: no working-tree path to scan
        # renames/copies carry (old, new): the new path is the live one
        add(parts[-1])

    untracked = subprocess.run(
        ["git", "-C", str(repo), "ls-files", "--others", "--exclude-standard"],
        capture_output=True,
        text=True,
        check=True,
    ).stdout
    for line in untracked.splitlines():
        add(line)
    return sorted(set(files))


def main(argv: List[str]) -> int:
    args = list(argv[1:])
    repo = _REPO
    if "--repo" in args:
        i = args.index("--repo")
        repo = pathlib.Path(args[i + 1]).resolve()
        del args[i : i + 2]
    if "--changed" in args:
        args.remove("--changed")
        changed = _changed_py_files(repo)
        if not changed:
            print("lint: no changed .py files")
            return 0
        args.extend(changed)
    return analysis_main(["lint", "--root", str(repo), *args])


if __name__ == "__main__":
    sys.exit(main(sys.argv))
