"""Registered-stage Gibbs timing: materialized-scan path (round 4) vs
the fused gated FFBS kernels (round 5).

VERDICT r4 ask 1's done criterion: the soft-gate conjugate Gibbs arm at
the registered-stage shape (16 chains, T = 8,386-leg window — budgets
were sized on a synthetic window of the real shape, per
`docs/phi_protocol.md` provenance notes) must run >= 5x faster than the
round-4 scan path (~40 ms/draw). The old path is reproduced exactly by
a subclass whose ``gate_keys`` returns None: ``sample_gibbs`` then
takes ``build`` (materialized time-varying kernel) into scan-FFBS —
the round-4 dispatch.

Writes `results/gibbs_fused_timing.json`. Tunnel discipline: fresh PRNG
keys per timed call (byte-identical requests are memoized), timing via
block_until_ready + host reduction. Wall target < 5 min.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

OUT = os.path.join(
    os.path.dirname(__file__), "..", "results", "gibbs_fused_timing.json"
)


def synth_window(T, seed=0):
    """Tick-like (x, sign) at the registered window's shape: symbols
    0..8, ~1/3 same-sign adjacent legs (the real-data rate)."""
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 9, size=T).astype(np.int32)
    sign = np.zeros(T, np.int32)
    for t in range(1, T):
        sign[t] = sign[t - 1] ^ (rng.random() < 2 / 3)
    return jnp.asarray(x), jnp.asarray(sign)


def time_path(model, data, chains, draws, seed):
    from hhmm_tpu.infer.gibbs import GibbsConfig, sample_gibbs

    cfg = GibbsConfig(num_warmup=1, num_samples=draws, num_chains=chains)

    def run(key):
        qs, st = sample_gibbs(model, data, key, cfg)
        return st["logp"]

    lp = run(jax.random.PRNGKey(seed))  # compile + run
    float(np.asarray(lp.sum()))
    # monotonic clock only (check_guards invariant 5a)
    t0 = time.perf_counter()
    lp = run(jax.random.PRNGKey(seed + 1))  # fresh key: defeats memoization
    float(np.asarray(lp.sum()))
    dt = time.perf_counter() - t0
    return dt, dt / (draws + 1) * 1e3  # ms per sweep (all chains)


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    from hhmm_tpu.models import TayalHHMMLite

    T, chains = 8386, 16
    x, sign = synth_window(T)
    data = {"x": x, "sign": sign}

    class ScanPathTayal(TayalHHMMLite):
        """Round-4 dispatch: no gate keys -> materialized kernel + scan
        FFBS (`infer/gibbs.py` pre-round-5 behavior)."""

        def gate_keys(self, data):
            return None

    new = TayalHHMMLite()  # stan gate, gate keys -> fused chunked FFBS
    old = ScanPathTayal()

    rec = {"device": str(jax.devices()[0]), "ts": time.strftime("%F %T"),
           "shape": {"T": T, "chains": chains, "gate": "stan"}}
    dt_new, ms_new = time_path(new, data, chains, draws=400, seed=11)
    print(f"fused gated FFBS: {dt_new:.2f}s for 401 sweeps = {ms_new:.2f} ms/sweep",
          flush=True)
    dt_old, ms_old = time_path(old, data, chains, draws=50, seed=21)
    print(f"materialized scan: {dt_old:.2f}s for 51 sweeps = {ms_old:.2f} ms/sweep",
          flush=True)
    rec["fused"] = {"draws": 400, "wall_s": round(dt_new, 3),
                    "ms_per_sweep": round(ms_new, 3)}
    rec["scan_r4"] = {"draws": 50, "wall_s": round(dt_old, 3),
                      "ms_per_sweep": round(ms_old, 3)}
    rec["speedup"] = round(ms_old / ms_new, 2)
    print("speedup:", rec["speedup"], flush=True)
    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main()
