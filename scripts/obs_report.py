#!/usr/bin/env python
"""Render one run's manifest + metrics export as a text dashboard.

The consumer end of the observability stack: `obs/manifest.py` pins
what ran, `obs/trace.py` where the time went, `obs/telemetry.py` what
XLA compiled, and `obs/metrics.py` the statistical health (interim
convergence, divergence/quarantine counters, serving staleness/drift,
SLO attainment). This script folds all of it into one readable report:

  == run ==          host, stack, hardware, git, workload digest
  == spans ==        hottest-first span table (count/total/p50/p99)
  == compile ==      backend compiles, per-phase seconds, per-entry-point
                     jit cache sizes, component scopes
  == memory ==       per-device peak watermarks (where exposed)
  == plan ==         the execution planner's resolved layout (PR 6,
                     `hhmm_tpu/plan/`): mesh axes, chunk, bucket ladder,
                     time-parallel branch, idle-device rationale
  == kernel costs == the `obs/profile.py` cost plane: per-kernel device
                     time, FLOPs, roofline fraction, and which dispatch
                     branches are DB-backed vs table-backed vs unmeasured
  == convergence ==  the per-chunk interim R̂/ESS/divergence/quarantine
                     trajectory a traced `batch/fit.py` run emits
  == serving ==      tick latency, throughput, staleness, drift alarms,
                     overload/resilience counters (shed/pager/device loss)
  == request timeline == the `obs/request.py` plane: per-tenant tick
                     latency decomposed into queue/device/other shares,
                     windowed p50/p99, sheds, the fairness
                     observables (p99 spread, queue age, interleaving),
                     and the scheduler's flush-order attribution table
                     (per-tenant share/served/stranded/credit)
  == pipeline ==     the `hhmm_tpu/pipeline/` async flush plane
                     (`bench.py --pipeline`): in-flight dispatch/harvest
                     depth, the sync-vs-async overlap duel verdict
                     (queue share, hidden device time, bitwise parity),
                     consistent-hash placement and the per-device
                     served table
  == storm ==        the `bench.py --serve-storm` verdict: faults
                     injected/escaped + survival gates, fairness arms
                     incl. the FIFO-vs-DRR duel, warm page-in parity
  == maintenance ==  the `hhmm_tpu/maint/` closed loop (`bench.py
                     --maint`): drift triggers -> warm refits ->
                     shadow verdicts -> promotions, with the recent
                     event table and the LOOP CLOSED verdict
  == analysis ==     the `hhmm_tpu.analysis` static-analyzer verdict:
                     per-family + per-rule finding/suppression counts,
                     the lock-order DAG verdict (ACYCLIC/CYCLES), and
                     the zero-unsuppressed-findings assertion (embedded
                     `analysis` stanza or `--analysis report.json`)
  == slo ==          per-check PASS/FAIL + overall attainment

Inputs: the full manifest JSON (``bench.py --manifest-out`` /
``results/manifest_bench_<mode>.json`` under ``HHMM_TPU_TRACE=1``),
which embeds the metrics snapshot; ``--metrics`` optionally points at a
JSONL export (`MetricsRegistry.export_jsonl`) to use instead — e.g. a
scrape taken mid-run.

No jax import (asserted by ``tests/test_obs.py``) — this renders
records on CI hosts and laptops that have neither an accelerator nor
the pinned jax. Exit 0 on success, 2 on unreadable input.

Usage::

    python scripts/obs_report.py results/manifest_bench_serve.json
    python scripts/obs_report.py MANIFEST --metrics run.metrics.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# ---- formatting helpers ----


def _table(headers: Tuple[str, ...], rows: List[Tuple[str, ...]], out) -> None:
    if not rows:
        print("  (empty)", file=out)
        return
    widths = [
        max(len(headers[i]), *(len(r[i]) for r in rows)) for i in range(len(headers))
    ]
    fmt = "  " + "  ".join(f"{{:<{w}}}" for w in widths)
    print(fmt.format(*headers), file=out)
    print(fmt.format(*("-" * w for w in widths)), file=out)
    for r in rows:
        print(fmt.format(*r), file=out)


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def _section(title: str, out) -> None:
    print(f"\n== {title} ==", file=out)


# ---- metrics helpers ----


def parse_metric_key(key: str) -> Tuple[str, Dict[str, str]]:
    """``name{k=v,...}`` → (name, labels) — the snapshot key format."""
    name, _, rest = key.partition("{")
    labels: Dict[str, str] = {}
    if rest:
        for pair in rest.rstrip("}").split(","):
            k, _, v = pair.partition("=")
            labels[k] = v
    return name, labels


def load_metrics_jsonl(path: str) -> Dict[str, Dict[str, Any]]:
    """JSONL export → the snapshot dict shape (keyed by rendered key)."""
    out: Dict[str, Dict[str, Any]] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            key = rec.pop("key", None) or rec.get("name", "?")
            rec.pop("name", None)
            rec.pop("labels", None)
            out[key] = rec
    return out


def hist_quantile(state: Dict[str, Any], q: float) -> float:
    """Conservative upper-edge quantile from an exported histogram
    state — mirrors `obs/metrics.Histogram.quantile` without numpy."""
    total = state.get("count", 0)
    if not total:
        return float("nan")
    target = max(q * total, 1e-300)
    cum = 0
    for edge, c in zip(state["edges"], state["counts"]):
        cum += c
        if cum >= target:
            return float(edge)
    return float("inf")


# ---- sections ----


def render_run(man: Dict[str, Any], out) -> None:
    _section("run", out)
    versions = man.get("versions") or {}
    git = man.get("git") or {}
    rows = [
        ("host", _fmt(man.get("hostname"))),
        (
            "hardware",
            f"{_fmt(man.get('backend'))} / {_fmt(man.get('device_kind'))}"
            f" x{_fmt(man.get('device_count'))}",
        ),
        (
            "stack",
            f"python {_fmt(versions.get('python'))}, "
            f"jax {_fmt(versions.get('jax'))}, "
            f"jaxlib {_fmt(versions.get('jaxlib'))}",
        ),
        (
            "git",
            f"{_fmt(git.get('rev'))[:12]}"
            + (" (dirty)" if git.get("dirty") else ""),
        ),
        ("seed", _fmt(man.get("seed"))),
        ("workload_digest", _fmt(man.get("workload_digest"))),
        ("trace_enabled", _fmt(man.get("trace_enabled"))),
        ("bench_mode", _fmt(man.get("bench_mode"))),
    ]
    _table(("field", "value"), rows, out)


def render_spans(man: Dict[str, Any], out) -> None:
    _section("spans (hottest first)", out)
    spans = man.get("spans") or {}
    rows = [
        (
            name,
            _fmt(t.get("count")),
            _fmt(t.get("total_s")),
            _fmt(t.get("p50_ms")),
            _fmt(t.get("p99_ms")),
            _fmt(t.get("max_ms")),
        )
        for name, t in spans.items()
    ]
    _table(("span", "count", "total_s", "p50_ms", "p99_ms", "max_ms"), rows, out)


def render_compile(man: Dict[str, Any], out) -> None:
    _section("compile", out)
    comp = man.get("compile") or {}
    print(
        f"  backend_compiles: {_fmt(comp.get('backend_compiles'))} "
        f"(listener {'on' if comp.get('listening') else 'off'})",
        file=out,
    )
    secs = comp.get("compile_seconds") or {}
    for phase, s in sorted(secs.items()):
        print(f"  {phase}: {_fmt(s)} s", file=out)
    sizes = comp.get("jit_cache_sizes") or {}
    if sizes:
        _table(
            ("jit entry point", "traced signatures"),
            [(k, _fmt(v)) for k, v in sorted(sizes.items())],
            out,
        )
    scopes = comp.get("scopes") or {}
    for label, v in sorted(scopes.items()):
        print(f"  scope {label}: {_fmt(v)}", file=out)


def render_memory(man: Dict[str, Any], out) -> None:
    peak = man.get("peak_memory") or {}
    _section("memory", out)
    if not peak:
        print("  (backend exposes no memory_stats)", file=out)
        return
    rows = []
    for dev, st in sorted(peak.items()):
        rows.append(
            (
                f"device {dev}",
                _fmt(st.get("bytes_in_use")),
                _fmt(st.get("peak_bytes_in_use")),
                _fmt(st.get("bytes_limit")),
            )
        )
    _table(("device", "bytes_in_use", "peak_bytes", "limit"), rows, out)


def _record_manifest(man: Dict[str, Any]) -> Dict[str, Any]:
    """The embedded record's compact manifest stanza, for stanzas
    (`slo`, `storm`, `kernel_costs`) that `bench.py` attaches to the
    record rather than the full manifest's top level."""
    rec = man.get("record")
    if isinstance(rec, dict) and isinstance(rec.get("manifest"), dict):
        return rec["manifest"]
    return {}


def render_plan(man: Dict[str, Any], out) -> None:
    """The execution planner's resolved-layout stanza (`hhmm_tpu/plan/`
    ``note_stanza("plan", ...)``, landed in PR 6): which mesh/chunk/
    branch actually ran, and why devices idled if any did."""
    plan = man.get("plan") or _record_manifest(man).get("plan")
    if not isinstance(plan, dict):
        return  # not a planned run: no section
    _section("plan", out)
    wl = plan.get("workload") or {}
    if wl:
        print(
            "  workload: "
            + " ".join(f"{k}={_fmt(wl.get(k))}" for k in ("B", "T", "C", "K")),
            file=out,
        )
    mesh = plan.get("mesh")
    if isinstance(mesh, dict) and mesh:
        mesh_s = " x ".join(f"{k}:{v}" for k, v in mesh.items())
    else:
        mesh_s = "none (single device)"
    print(
        f"  mesh: {mesh_s}  (devices used "
        f"{_fmt(plan.get('devices_used'))}/{_fmt(plan.get('devices'))} "
        f"on {_fmt(plan.get('platform'))})",
        file=out,
    )
    chunk, req = plan.get("chunk"), plan.get("chunk_requested")
    chunk_s = _fmt(chunk)
    if req is not None and req != chunk:
        chunk_s += f" (requested {_fmt(req)}, rounded to the series ways)"
    print(f"  chunk: {chunk_s}", file=out)
    buckets = plan.get("buckets")
    if buckets:
        print(
            f"  serve buckets: {buckets} (shard from "
            f"{_fmt(plan.get('shard_min_bucket'))} lanes)",
            file=out,
        )
    print(f"  time-parallel branch: {_fmt(plan.get('branch'))}", file=out)
    if plan.get("reason"):
        print(f"  rationale: {plan['reason']}", file=out)


def _pct(v: Any) -> str:
    return f"{100 * v:.1f}%" if isinstance(v, (int, float)) else "-"


def render_request(man: Dict[str, Any], out) -> None:
    """The request plane (`hhmm_tpu/obs/request.py`): per-tenant
    lifecycle decomposition + fairness observables."""
    req = man.get("request") or _record_manifest(man).get("request")
    if not isinstance(req, dict):
        return  # no lifecycle recorder in this run: no section
    _section("request timeline", out)
    rows = []
    for tenant, t in sorted((req.get("tenants") or {}).items()):
        if not isinstance(t, dict):
            continue
        rows.append(
            (
                tenant,
                _fmt(t.get("ticks")),
                _fmt(t.get("sheds")),
                _fmt(t.get("p50_ms")),
                _fmt(t.get("p99_ms")),
                _pct(t.get("queue_share")),
                _pct(t.get("device_share")),
                _pct(t.get("other_share")),
                _fmt(t.get("max_queue_depth")),
            )
        )
    _table(
        (
            "tenant",
            "ticks",
            "sheds",
            "p50_ms",
            "p99_ms",
            "queue",
            "device",
            "other",
            "max_q",
        ),
        rows,
        out,
    )
    omitted = req.get("tenants_omitted")
    if omitted:
        print(f"  (+{omitted} tenant(s) omitted from the stanza)", file=out)
    overall = req.get("overall") or {}
    if overall:
        print(
            f"  overall: {_fmt(overall.get('ticks'))} ticks, "
            f"{_fmt(overall.get('sheds'))} sheds — queue "
            f"{_pct(overall.get('queue_share'))}, device "
            f"{_pct(overall.get('device_share'))}, other "
            f"{_pct(overall.get('other_share'))} "
            f"(window {_fmt(req.get('window_s'))} s)",
            file=out,
        )
    fair = req.get("fairness") or {}
    if fair:
        print(
            f"  fairness: p99 spread {_fmt(fair.get('p99_spread_ms'))} ms, "
            f"max queue-age at dispatch {_fmt(fair.get('max_queue_age_ms'))} "
            f"ms, {_fmt(fair.get('mean_flush_tenants'))} tenants/flush over "
            f"{_fmt(fair.get('flushes'))} flushes",
            file=out,
        )
    profiled = req.get("profiled_device_ms") or {}
    for k, v in sorted(profiled.items()):
        print(f"  warm device re-time {k}: {_fmt(v)} ms", file=out)
    sched = req.get("scheduler")
    if isinstance(sched, dict):
        print(
            f"  flush order: {_fmt(sched.get('order'))} "
            f"(credit cap {_fmt(sched.get('credit_cap'))} ticks, last "
            f"flush {'>'.join(sched.get('last_flush_order') or []) or '-'})",
            file=out,
        )
        rows = []
        for tenant, t in sorted((sched.get("tenants") or {}).items()):
            if not isinstance(t, dict):
                continue
            rows.append(
                (
                    tenant,
                    _fmt(t.get("share")),
                    _fmt(t.get("served")),
                    _fmt(t.get("stranded")),
                    _fmt(t.get("credit")),
                    _fmt(t.get("credit_max")),
                )
            )
        _table(
            ("tenant", "share", "served", "stranded", "credit", "credit_max"),
            rows,
            out,
        )
    events = req.get("events")
    if isinstance(events, dict):
        print(
            f"  regime events: {_fmt(events.get('flips'))} flips, "
            f"{_fmt(events.get('drifts'))} drift alarms "
            f"(serve/events.py feed)",
            file=out,
        )
        rows = []
        for tenant, t in sorted((events.get("tenants") or {}).items()):
            if not isinstance(t, dict):
                continue
            rows.append((tenant, _fmt(t.get("flips")), _fmt(t.get("drifts"))))
        _table(("tenant", "flips", "drifts"), rows, out)


def render_kernel_costs(man: Dict[str, Any], out) -> None:
    """The `obs/profile.py` cost plane: measured device time + XLA cost
    analysis per kernel/branch, and the dispatch-source audit — which
    ``"auto"`` branches rest on a measured DB row, which on the
    checked-in table, which on nothing (`kernels/dispatch.py`)."""
    _section("kernel costs", out)
    kc = man.get("kernel_costs") or _record_manifest(man).get("kernel_costs")
    if not isinstance(kc, dict):
        print("  (no kernel-cost rows in this run)", file=out)
        return
    rows = []
    for r in kc.get("rows") or []:
        if not isinstance(r, dict):
            continue
        frac = r.get("flops_frac")
        rows.append(
            (
                f"{_fmt(r.get('kernel'))}[{_fmt(r.get('branch'))}]",
                _fmt(r.get("K")),
                _fmt(r.get("T")),
                _fmt(r.get("B")),
                _fmt(r.get("dtype")),
                _fmt(r.get("p50_ms")),
                _fmt(r.get("flops")),
                "-" if not isinstance(frac, (int, float)) else f"{100 * frac:.4g}%",
                "timing-only" if r.get("timing_only") else "",
            )
        )
    _table(
        ("kernel", "K", "T", "B", "dtype", "p50_ms", "flops", "flops_peak", ""),
        rows,
        out,
    )
    src_label = {
        "db": "DB-backed",
        "table": "table-backed",
        "plan": "plan-pinned",
        "default": "unmeasured (scan default)",
    }
    branches = kc.get("branches")
    if isinstance(branches, (list, tuple)) and branches:
        print(f"  raced branches: {'/'.join(str(b) for b in branches)}", file=out)
    for d in kc.get("dispatch") or []:
        if not isinstance(d, dict):
            continue
        raced = d.get("raced")
        tail = (
            f" [raced {'/'.join(str(b) for b in raced)}]"
            if isinstance(raced, (list, tuple)) and raced
            else ""
        )
        print(
            f"  auto {_fmt(d.get('kernel'))} K={_fmt(d.get('K'))} "
            f"T={_fmt(d.get('T'))}: {_fmt(d.get('auto'))} "
            f"({src_label.get(d.get('source'), _fmt(d.get('source')))})"
            f"{tail}",
            file=out,
        )
    if kc.get("db_path"):
        print(f"  cost DB: {kc['db_path']}", file=out)


def render_pipeline(man: Dict[str, Any], out) -> None:
    """The async flush pipeline (`hhmm_tpu/pipeline/`): in-flight
    dispatch/harvest depth from the request stanza, the sync-vs-async
    overlap duel verdict (``bench.py --pipeline``), consistent-hash
    placement and the per-device fan-out table."""
    pipe = man.get("pipeline") or _record_manifest(man).get("pipeline")
    req = man.get("request") or _record_manifest(man).get("request")
    flight = req.get("pipeline") if isinstance(req, dict) else None
    if not isinstance(pipe, dict) and not isinstance(flight, dict):
        return  # no async pipeline in this run: no section
    _section("pipeline", out)
    if isinstance(flight, dict):
        print(
            f"  in-flight: depth {_fmt(flight.get('in_flight_depth'))} "
            f"(peak {_fmt(flight.get('in_flight_peak'))}), "
            f"{_fmt(flight.get('harvested_flights'))} flight(s) harvested",
            file=out,
        )
    if not isinstance(pipe, dict):
        return
    if "async_queue_share" in pipe:
        print(
            "  overlap duel: queue share sync "
            f"{_pct(pipe.get('sync_queue_share'))} -> async "
            f"{_pct(pipe.get('async_queue_share'))}, hidden "
            f"{_pct(pipe.get('overlap_share'))} of device time, "
            f"{_fmt(pipe.get('parity_mismatches'))} parity mismatch(es) — "
            + ("OK" if pipe.get("ok") else "REGRESSED"),
            file=out,
        )
    # prefer the serving fleet's own counters (the main replay) over
    # the duel's synthetic cohort when both are present
    fleet = pipe.get("fleet")
    src = fleet if isinstance(fleet, dict) else pipe
    if src is fleet and "overlap_share" in src:
        print(
            f"  replay overlap share: {_pct(src.get('overlap_share'))}",
            file=out,
        )
    placement = src.get("placement")
    if isinstance(placement, dict) and placement:
        print(
            f"  placement: {_fmt(placement.get('algo'))} over "
            f"{_fmt(src.get('n_devices'))} device(s), "
            f"{_fmt(src.get('deferred_ticks'))} tick(s) deferred by the "
            "fold-order guard",
            file=out,
        )
    served = src.get("per_device_served")
    if isinstance(served, dict) and served:
        rows = [
            (str(d), _fmt(n))
            for d, n in sorted(served.items(), key=lambda kv: str(kv[0]))
        ]
        _table(("device", "served"), rows, out)


def render_storm(man: Dict[str, Any], out) -> None:
    """The ``--serve-storm`` stanza (`bench.py`): injected-fault plan,
    escaped-fault count, the survival gates — the section this
    report silently dropped before it learned the PR 7 schema — and
    the two-tenant fairness arms (balanced probe vs skewed storm)."""
    storm = man.get("storm") or _record_manifest(man).get("storm")
    if not isinstance(storm, dict):
        return  # not a storm run: no section (unlike slo, storms are rare)
    _section("storm", out)
    esc = storm.get("faults_escaped")
    print(f"  faults escaped: {_fmt(esc)}", file=out)
    fair = storm.get("fairness")
    if isinstance(fair, dict):
        print(
            "  fairness arms: skewed p99 spread "
            f"{_fmt(fair.get('skewed_p99_spread_ms'))} ms vs balanced "
            f"{_fmt(fair.get('balanced_p99_spread_ms'))} ms",
            file=out,
        )
        if "drr_p99_spread_ms" in fair:
            print(
                "  fairness duel: fifo "
                f"{_fmt(fair.get('fifo_p99_spread_ms'))} ms -> drr "
                f"{_fmt(fair.get('drr_p99_spread_ms'))} ms (balanced arm "
                f"{_fmt(fair.get('probe_balanced_p99_spread_ms'))} ms, "
                f"storm order {_fmt(fair.get('flush_order'))})",
                file=out,
            )
    wpi = storm.get("warm_page_in")
    if isinstance(wpi, dict):
        print(
            "  warm page-in: "
            + ("parity" if wpi.get("parity") else "MISMATCH")
            + f" over {_fmt(wpi.get('ticks'))} ticks (loglik delta "
            f"{_fmt(wpi.get('loglik_delta'))}, page-ins "
            f"{_fmt(wpi.get('warm_page_ins'))})",
            file=out,
        )
    inj = storm.get("faults_injected") or {}
    if isinstance(inj, dict):
        for name, spec in sorted(inj.items()):
            print(f"  injected {name}: {_fmt(spec)}", file=out)
    failed = storm.get("gates_failed")
    if failed:
        for g in failed:
            print(f"  gate FAILED: {g}", file=out)
        print("  verdict: FAILED", file=out)
    else:
        print("  verdict: SURVIVED", file=out)


def render_maint(man: Dict[str, Any], out) -> None:
    """The ``maint`` stanza (`hhmm_tpu/maint/`, `bench.py --maint`):
    the drift→refit→shadow→promote ladder's counters and the recent
    event window — how many alarms became refits, how many candidates
    won shadow evaluation and were promoted, and what each promotion's
    paired predictive-loglik verdict was."""
    maint = man.get("maint") or _record_manifest(man).get("maint")
    if not isinstance(maint, dict):
        return  # no maintenance plane in this run: no section
    _section("maintenance", out)
    for key, label in (
        ("triggers", "triggers (alarm/staleness -> refit request)"),
        ("refits", "warm refits"),
        ("promotions", "promotions"),
        ("shadow_rejections", "shadow rejections"),
        ("skipped_refits", "skipped refits"),
        ("failed_swaps", "failed swaps"),
        ("refit_seconds", "refit seconds"),
        ("dropped_triggers", "dropped triggers"),
        ("pending", "pending requests"),
    ):
        if key in maint:
            print(f"  {label}: {_fmt(maint.get(key))}", file=out)
    events = maint.get("events")
    if isinstance(events, list) and events:
        rows = []
        for e in events:
            if not isinstance(e, dict):
                continue
            shadow = e.get("shadow") or {}
            rows.append(
                (
                    _fmt(e.get("tick")),
                    _fmt(e.get("series")),
                    _fmt(e.get("outcome")),
                    _fmt(e.get("trigger") or e.get("reason")),
                    _fmt(shadow.get("mean_delta")),
                )
            )
        _table(("tick", "series", "outcome", "trigger", "shadow Δ/tick"), rows, out)
    promos = maint.get("promotions")
    if isinstance(promos, (int, float)):
        print(
            "  verdict: "
            + ("LOOP CLOSED" if promos > 0 else "NO PROMOTIONS"),
            file=out,
        )


def render_adapt(man: Dict[str, Any], out) -> None:
    """The ``adapt`` stanza (`hhmm_tpu/adapt/`, `bench.py --adapt`):
    the reweight→rejuvenate→refit ladder's counters, the per-series
    streaming-ESS table, the recent rejuvenation/escalation events,
    and the TRACKING/STALE verdict (did the adapted mixture beat the
    uniform-stale arm on the post-shift ticks)."""
    adapt = man.get("adapt") or _record_manifest(man).get("adapt")
    if not isinstance(adapt, dict):
        return  # no adaptation plane in this run: no section
    _section("adaptation", out)
    for key, label in (
        ("ess_floor_frac", "ESS floor (fraction of D)"),
        ("forget", "forgetting exponent"),
        ("shrink", "Liu-West shrink a"),
        ("escalate_after", "escalate after (strikes)"),
        ("reweight_ticks", "reweighted ticks"),
        ("rejuvenations", "rejuvenations"),
        ("escalations", "escalations (-> refit queue)"),
        ("ess_min", "ESS min (window)"),
        ("floor_breaches", "series below floor"),
        ("paired_mean_delta", "paired mean delta (nats/tick)"),
        ("pooled_mean_delta", "pooled mean delta (nats/tick)"),
        ("refits_adaptive", "refits (adaptive arm)"),
        ("refits_baseline", "refits (refit-only baseline)"),
    ):
        if key in adapt:
            print(f"  {label}: {_fmt(adapt.get(key))}", file=out)
    ess = adapt.get("ess")
    if isinstance(ess, list) and ess:
        rows = [
            (_fmt(e.get("series")), _fmt(e.get("ess")))
            for e in ess
            if isinstance(e, dict)
        ]
        _table(("series", "ESS"), rows, out)
    events = adapt.get("events")
    if isinstance(events, list) and events:
        rows = []
        for e in events:
            if not isinstance(e, dict):
                continue
            rows.append(
                (
                    _fmt(e.get("tick")),
                    _fmt(e.get("series")),
                    _fmt(e.get("kind")),
                    _fmt(e.get("reason") or e.get("strikes")),
                    _fmt(e.get("ess_before")),
                    _fmt(e.get("ess_after")),
                )
            )
        _table(
            ("tick", "series", "kind", "reason", "ESS before", "ESS after"),
            rows,
            out,
        )
    if "tracking_advantage" in adapt:
        print(
            "  verdict: "
            + ("TRACKING" if adapt.get("tracking_advantage") else "STALE"),
            file=out,
        )


def render_convergence(metrics: Dict[str, Dict[str, Any]], out) -> None:
    _section("convergence (interim, per fit chunk)", out)
    by_chunk: Dict[str, Dict[str, Any]] = {}
    for key, state in metrics.items():
        name, labels = parse_metric_key(key)
        if name.startswith("fit.interim.") and "chunk" in labels:
            by_chunk.setdefault(labels["chunk"], {})[
                name[len("fit.interim.") :]
            ] = state.get("value")
    rows = []
    for chunk in sorted(by_chunk, key=lambda c: (len(c), c)):
        vals = by_chunk[chunk]
        rows.append(
            (
                chunk,
                _fmt(vals.get("rhat_max")),
                _fmt(vals.get("ess_min")),
                _fmt(vals.get("divergence_rate")),
                _fmt(vals.get("quarantined_series")),
            )
        )
    _table(
        ("chunk", "rhat_max", "ess_min", "div_rate", "quarantined"), rows, out
    )
    totals = [
        ("fit.chunks", "chunks"),
        ("fit.divergences", "divergences"),
        ("fit.quarantined_series", "quarantined series"),
        ("fit.heal_attempts", "heal attempts"),
        ("fit.healed_series", "healed series"),
        ("fit.unhealed_series", "unhealed series"),
    ]
    for key, label in totals:
        if key in metrics:
            print(f"  total {label}: {_fmt(metrics[key].get('value'))}", file=out)
    for key, state in sorted(metrics.items()):
        name, labels = parse_metric_key(key)
        if name in ("infer.divergences", "infer.quarantined_chains"):
            print(
                f"  {name}[{labels.get('sampler', '?')}]: "
                f"{_fmt(state.get('value'))}",
                file=out,
            )


def render_serving(metrics: Dict[str, Dict[str, Any]], out) -> None:
    _section("serving", out)
    lat = metrics.get("serve.tick_latency_seconds")
    if lat and lat.get("type") == "histogram":
        p50, p99 = hist_quantile(lat, 0.5), hist_quantile(lat, 0.99)
        print(
            f"  tick latency: p50 {p50 * 1e3:g} ms, p99 {p99 * 1e3:g} ms "
            f"({lat.get('count', 0)} requests)",
            file=out,
        )
    simple = [
        ("serve.ticks", "ticks"),
        ("serve.flushes", "flushes"),
        ("serve.busy_seconds", "busy seconds"),
        ("serve.degraded_responses", "degraded responses"),
        ("serve.degraded_attaches", "degraded attaches"),
        ("serve.superseded_responses", "superseded responses"),
        ("serve.snapshot_staleness_seconds", "snapshot staleness (s)"),
        ("serve.drift_alarms", "drift alarms"),
        # the PR 7 overload/failure ladder: every rung is a counted,
        # degraded-not-raised outcome — render them or the report
        # claims a storm run served clean traffic
        ("serve.shed_ticks", "shed ticks"),
        ("serve.rejected_attaches", "rejected attaches"),
        ("serve.dispatch_errors", "dispatch errors"),
        ("serve.device_loss_events", "device loss events"),
        ("serve.pager_evictions", "pager evictions"),
        ("serve.pager_reloads", "pager reloads"),
        ("serve.pager_resident_bytes", "pager resident bytes"),
        ("serve.profiled_flushes", "profiled flushes"),
        # transfer telemetry (device-resident carry plane): what the
        # serving path actually moved across the host/device boundary
        ("serve.h2d_bytes", "h2d bytes"),
        ("serve.d2h_bytes", "d2h bytes"),
        ("serve.carry_resident_bytes", "carry resident bytes"),
    ]
    seen = False
    for key, label in simple:
        if key in metrics:
            seen = True
            print(f"  {label}: {_fmt(metrics[key].get('value'))}", file=out)
    if not seen and not lat:
        print("  (no serving metrics in this run)", file=out)


def render_analysis(analysis: Optional[Dict[str, Any]], out) -> None:
    """The `hhmm_tpu.analysis` static-analyzer verdict (``--format
    json`` report, embedded at manifest key ``analysis`` or passed via
    ``--analysis``): per-family and per-rule finding/suppression
    counts, the lock-order DAG verdict, and the
    zero-unsuppressed-findings assertion tier-1 runs under."""
    _section("analysis", out)
    if not isinstance(analysis, dict):
        print("  (no static-analysis report in this run)", file=out)
        return
    rules = analysis.get("rules") or {}
    print(
        f"  files: {_fmt(analysis.get('files_scanned'))}   "
        f"rules: {len(rules)}   "
        f"findings: {len(analysis.get('findings') or [])}   "
        f"suppressed: {_fmt(analysis.get('suppressed_count'))}   "
        f"allowlist: {_fmt(analysis.get('allowlist_entries'))}",
        file=out,
    )
    # per-family rollup (reports predating rule families fold into
    # "unknown" — the per-rule table below still carries them)
    fams: Dict[str, Dict[str, int]] = {}
    for rid, stats in rules.items():
        fam = str(stats.get("family") or "unknown")
        agg = fams.setdefault(fam, {"rules": 0, "findings": 0, "suppressed": 0})
        agg["rules"] += 1
        agg["findings"] += int(stats.get("findings") or 0)
        agg["suppressed"] += int(stats.get("suppressed") or 0)
    if fams:
        _table(
            ("family", "rules", "findings", "suppressed"),
            [
                (fam, str(a["rules"]), str(a["findings"]), str(a["suppressed"]))
                for fam, a in sorted(fams.items())
            ],
            out,
        )
    rows = []
    for rid, stats in sorted(rules.items()):
        if not (stats.get("findings") or stats.get("suppressed")):
            continue
        rows.append(
            (
                rid,
                _fmt(stats.get("severity")),
                _fmt(stats.get("findings")),
                _fmt(stats.get("suppressed")),
            )
        )
    if rows:
        _table(("rule", "severity", "findings", "suppressed"), rows, out)
    for f in (analysis.get("findings") or [])[:20]:
        loc = f"{f.get('file')}:{f.get('line')}" if f.get("line") else f.get("file")
        print(f"  {loc}: [{f.get('rule_id')}] {f.get('message')}", file=out)
    unused = analysis.get("allowlist_unused") or []
    if unused:
        print(f"  unused allowlist entries: {', '.join(map(str, unused))}", file=out)
    lock_order = (analysis.get("extras") or {}).get("lock_order")
    if isinstance(lock_order, dict):
        verdict = _fmt(lock_order.get("verdict"))
        print(
            f"  lock-order: {verdict}   "
            f"locks: {len(lock_order.get('locks') or [])}   "
            f"edges: {len(lock_order.get('edges') or [])}",
            file=out,
        )
        for cyc in lock_order.get("cycles") or []:
            print(f"    cycle: {' -> '.join(map(str, cyc))}", file=out)
    clean = bool(analysis.get("ok"))
    print(
        "  verdict: "
        + ("CLEAN (zero unsuppressed findings)" if clean else "FINDINGS"),
        file=out,
    )


def render_slo(man: Dict[str, Any], out) -> bool:
    _section("slo", out)
    slo = man.get("slo")
    if slo is None:
        rec = man.get("record")
        if isinstance(rec, dict):
            slo = (rec.get("manifest") or {}).get("slo")
    if not isinstance(slo, dict):
        print("  (no SLO verdict in this run)", file=out)
        return True
    rows = []
    for name, c in sorted((slo.get("checks") or {}).items()):
        rows.append(
            (
                name,
                _fmt(c.get("observed")),
                _fmt(c.get("limit")),
                "PASS" if c.get("ok") else "FAIL"
                + (f" ({c['reason']})" if c.get("reason") else ""),
            )
        )
    _table(("check", "observed", "limit", "verdict"), rows, out)
    attained = bool(slo.get("attained"))
    print(f"  overall: {'ATTAINED' if attained else 'UNMET'}", file=out)
    return attained


def render(
    man: Dict[str, Any],
    metrics: Dict[str, Dict[str, Any]],
    out,
    analysis: Optional[Dict[str, Any]] = None,
) -> None:
    print("hhmm_tpu run report", file=out)
    render_run(man, out)
    render_spans(man, out)
    render_compile(man, out)
    render_memory(man, out)
    render_plan(man, out)
    render_kernel_costs(man, out)
    render_convergence(metrics, out)
    render_serving(metrics, out)
    render_request(man, out)
    render_pipeline(man, out)
    render_storm(man, out)
    render_maint(man, out)
    render_adapt(man, out)
    render_analysis(analysis if analysis is not None else man.get("analysis"), out)
    render_slo(man, out)


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("manifest", help="full run manifest JSON (obs/manifest.py)")
    ap.add_argument(
        "--metrics",
        default=None,
        metavar="JSONL",
        help="metrics JSONL export to render instead of the manifest's "
        "embedded snapshot (MetricsRegistry.export_jsonl)",
    )
    ap.add_argument(
        "--analysis",
        default=None,
        metavar="JSON",
        help="hhmm_tpu.analysis --format json report to render instead "
        "of the manifest's embedded `analysis` stanza",
    )
    args = ap.parse_args(argv[1:])
    try:
        with open(args.manifest) as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"obs_report: cannot read manifest {args.manifest}: {e}", file=sys.stderr)
        return 2
    if not isinstance(man, dict):
        print(f"obs_report: {args.manifest} is not a manifest object", file=sys.stderr)
        return 2
    metrics: Dict[str, Dict[str, Any]] = {}
    if args.metrics is not None:
        try:
            metrics = load_metrics_jsonl(args.metrics)
        except (OSError, json.JSONDecodeError) as e:
            print(
                f"obs_report: cannot read metrics {args.metrics}: {e}",
                file=sys.stderr,
            )
            return 2
    else:
        metrics = man.get("metrics") or {}
    analysis: Optional[Dict[str, Any]] = None
    if args.analysis is not None:
        try:
            with open(args.analysis) as f:
                analysis = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(
                f"obs_report: cannot read analysis report {args.analysis}: {e}",
                file=sys.stderr,
            )
            return 2
    render(man, metrics, sys.stdout, analysis=analysis)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
