"""Measure the sublane-packed FFBS kernel vs the resident kernel on
the headline-bench shape (VERDICT r4 ask 5).

B=256, T=1024, K=4, dense masks — the exact shape of the bench's Gibbs
FFBS launches (the bench runs the HARD gate, which masks emissions and
dispatches the UNGATED kernel; a gated row is measured too for the
gate-key workloads that fit the resident bound). Records per-call wall
times and speedups into `results/pack2_timing.json`; the dispatcher
only adopts pack2 where this measurement says it wins. Tunnel
discipline: fresh pre-generated device uniforms per timed call (host
RNG + H2D stay OUTSIDE the timed window), block_until_ready + host
reduction. Wall target < 4 min.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

OUT = os.path.join(os.path.dirname(__file__), "..", "results", "pack2_timing.json")


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    from hhmm_tpu.kernels.pallas_ffbs import pallas_ffbs
    from hhmm_tpu.kernels.pallas_ffbs_pack2 import pallas_ffbs_pack2

    rng = np.random.default_rng(7)
    B, T, K = 256, 1024, 4
    log_pi = jnp.asarray(np.log(rng.dirichlet(np.ones(K), B)), jnp.float32)
    log_A = jnp.asarray(np.log(rng.dirichlet(np.ones(K), (B, K))), jnp.float32)
    log_obs = jnp.asarray(rng.normal(size=(B, T, K)) - 1.0, jnp.float32)
    mask = jnp.ones((B, T), jnp.float32)
    gate = jnp.asarray(rng.integers(0, 2, size=(B, T)), jnp.float32)
    skey = jnp.asarray(np.tile((np.arange(K) % 2).astype(np.float32), (B, 1)))

    rec = {"device": str(jax.devices()[0]), "ts": time.strftime("%F %T"),
           "shape": {"B": B, "T": T, "K": K}}
    reps = 30
    for mode, gargs in (("ungated", ()), ("gated", (gate, skey))):
        fns = {
            "resident": jax.jit(pallas_ffbs),
            "pack2": jax.jit(pallas_ffbs_pack2),
        }
        times = {}
        for name, fn in fns.items():
            # pre-generate every rep's uniforms ON DEVICE before the
            # timer: fresh inputs defeat tunnel memoization without
            # paying host RNG + transfer inside the measured window
            us = [
                jax.device_put(
                    jnp.asarray(rng.uniform(size=(B, T)), jnp.float32)
                )
                for _ in range(reps + 1)
            ]
            jax.block_until_ready(us)
            z, ll = fn(log_pi, log_A, log_obs, mask, us[-1], *gargs)  # compile
            float(np.asarray(ll.sum()))
            # monotonic clock only (check_guards invariant 5a): these
            # per-call times feed the dispatcher's adoption decision
            t0 = time.perf_counter()
            for r in range(reps):
                z, ll = fn(log_pi, log_A, log_obs, mask, us[r], *gargs)
                float(np.asarray(ll.sum()))
            dt = (time.perf_counter() - t0) / reps
            times[name] = dt
            print(f"{mode}/{name}: {dt * 1e3:.2f} ms/call", flush=True)
        # parity on device: same uniforms -> same draws
        u = jnp.asarray(rng.uniform(size=(B, T)), jnp.float32)
        z_r, ll_r = fns["resident"](log_pi, log_A, log_obs, mask, u, *gargs)
        z_p, ll_p = fns["pack2"](log_pi, log_A, log_obs, mask, u, *gargs)
        rec[mode] = {
            "resident_ms": round(times["resident"] * 1e3, 3),
            "pack2_ms": round(times["pack2"] * 1e3, 3),
            "speedup_pack2": round(times["resident"] / times["pack2"], 3),
            "device_parity": {
                "z_mismatch_steps": int(
                    (np.asarray(z_r) != np.asarray(z_p)).sum()
                ),
                "ll_maxdev": float(
                    np.max(np.abs(np.asarray(ll_r) - np.asarray(ll_p)))
                ),
            },
        }
        print(mode, "speedup:", rec[mode]["speedup_pack2"],
              "parity:", rec[mode]["device_parity"], flush=True)
    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main()
