"""Measure the blocked semiring FFBS kernel across block sizes on the
headline-bench shape (formerly the pack2-vs-resident probe; the
sublane-packed experiment is retired — `kernels/pallas_ffbs_pack2.py`
is a deprecated shim over the unified kernel, so the open tuning knob
at this shape is now ``t_block``).

B=256, T=1024, K=4, dense masks — the exact shape of the bench's Gibbs
FFBS launches (the bench runs the HARD gate, which masks emissions and
dispatches the UNGATED kernel; a gated row is measured too for the
gate-key workloads that fit the single-block bound). Records per-call
wall times and speedups vs the single-block (resident) schedule into
`results/pack2_timing.json`; `docs/parallel_scan.md`'s block-size
guidance is anchored on this measurement. Tunnel discipline: fresh
pre-generated device uniforms per timed call (host RNG + H2D stay
OUTSIDE the timed window), timing through the canonical
``device_time`` harness (`obs/profile.py`). Wall target < 4 min.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:  # runnable as `python scripts/tpu_pack2_probe.py`
    sys.path.insert(0, _ROOT)

OUT = os.path.join(os.path.dirname(__file__), "..", "results", "pack2_timing.json")

BLOCKS = (128, 256, 512, 1024)  # 1024 = single-block (resident) at T=1024


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    # the sanctioned Pallas entry (analysis rule pallas-import)
    from hhmm_tpu.kernels.dispatch import semiring_ffbs
    from hhmm_tpu.obs import profile as obs_profile

    rng = np.random.default_rng(7)
    B, T, K = 256, 1024, 4
    log_pi = jnp.asarray(np.log(rng.dirichlet(np.ones(K), B)), jnp.float32)
    log_A = jnp.asarray(np.log(rng.dirichlet(np.ones(K), (B, K))), jnp.float32)
    log_obs = jnp.asarray(rng.normal(size=(B, T, K)) - 1.0, jnp.float32)
    mask = jnp.ones((B, T), jnp.float32)
    gate = jnp.asarray(rng.integers(0, 2, size=(B, T)), jnp.float32)
    skey = jnp.asarray(np.tile((np.arange(K) % 2).astype(np.float32), (B, 1)))

    rec = {"device": str(jax.devices()[0]), "ts": time.strftime("%F %T"),
           "shape": {"B": B, "T": T, "K": K}, "blocks": list(BLOCKS)}
    reps = 30
    for mode, gargs in (("ungated", ()), ("gated", (gate, skey))):
        times = {}
        z_by_block = {}
        for t_block in BLOCKS:
            fn = jax.jit(
                lambda lp, lA, lo, m, u, *g, tb=t_block: semiring_ffbs(
                    lp, lA, lo, m, u, *g, t_block=tb
                )
            )
            # pre-generate every rep's uniforms ON DEVICE before the
            # timer: fresh inputs defeat tunnel memoization without
            # paying host RNG + transfer inside the measured window
            us = [
                jax.device_put(
                    jnp.asarray(rng.uniform(size=(B, T)), jnp.float32)
                )
                for _ in range(reps + 1)
            ]
            jax.block_until_ready(us)
            sets = [(log_pi, log_A, log_obs, mask, u) + gargs for u in us]
            t = obs_profile.device_time(fn, arg_sets=sets, reps=reps)
            times[t_block] = t.p50_s
            print(f"{mode}/t_block={t_block}: {t.p50_s * 1e3:.2f} ms/call",
                  flush=True)
            # parity across schedules: same uniforms -> same draws
            z, _ = fn(log_pi, log_A, log_obs, mask, us[0], *gargs)
            z_by_block[t_block] = np.asarray(z)
        resident = times[max(BLOCKS)]
        z_ref = z_by_block[max(BLOCKS)]
        rec[mode] = {
            f"t{b}_ms": round(times[b] * 1e3, 3) for b in BLOCKS
        }
        rec[mode]["best_block"] = int(min(times, key=times.get))
        rec[mode]["speedup_best_vs_resident"] = round(
            resident / min(times.values()), 3
        )
        rec[mode]["z_mismatch_steps"] = int(
            sum((z_by_block[b] != z_ref).sum() for b in BLOCKS)
        )
        print(mode, rec[mode], flush=True)
    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main()
