#!/usr/bin/env python
"""Static robustness pass (tier-1, no JAX import — pure ``ast``).

Asserts the invariants the fault-tolerance subsystem
(`docs/robustness.md`) and the streaming service (`docs/serving.md`)
depend on:

1. **No bare ``except:``** anywhere under ``hhmm_tpu/`` (the serving
   layer included) — a bare handler swallows
   ``KeyboardInterrupt``/``SystemExit`` and, worse, masks the device
   faults the retry layer (`robust/retry.py`) must *see* to classify
   (UNAVAILABLE vs deterministic). Catch concrete types.
2. **Every public sampler entry point routes through the chain-health
   guard**: each sampler module (`infer/run.py`, `infer/chees.py`,
   `infer/gibbs.py`) must import from ``hhmm_tpu.robust.guards`` and
   actually *call* a guard function — a sampler added (or refactored)
   without the guard would silently reintroduce NaN poisoning of vmapped
   batches.
3. **The online filter step routes through the guarded normalization**:
   ``serve/online.py`` must import ``safe_log_normalize`` from
   ``hhmm_tpu.core.lmath`` and call it — a streaming update normalized
   with a bare ``log_normalize`` would turn impossible evidence into
   NaN state instead of the −inf floor the scheduler's quarantine mask
   detects (`serve/scheduler.py`).
4. **Semiring combines use the guarded reduction**: the time-parallel
   kernels (`kernels/semiring.py`, `kernels/assoc.py`) must import
   ``safe_logsumexp`` from ``hhmm_tpu.core.lmath`` and call it, and
   must NOT touch any raw logsumexp — no ``jnp.logaddexp`` /
   ``jax.nn.logsumexp`` attribute access, no un-guarded ``logsumexp``
   import. Semiring *identity elements are −inf by construction*, so
   an all-identity fiber (masked run, impossible evidence) hits the
   all-(−inf) reduction edge case on every combine; a raw logsumexp
   there has NaN cotangents and, in naive forms, NaN values
   (docs/parallel_scan.md).

Exit 0 when clean, 1 with one line per violation. Run by
``tests/test_robust.py`` (and re-asserted by ``tests/test_serve.py``
and ``tests/test_assoc.py``) so the pass is enforced in tier-1.
"""

from __future__ import annotations

import ast
import pathlib
import sys
from typing import List

# sampler entry modules -> guard functions at least one of which must be
# both imported from hhmm_tpu.robust.guards and called
SAMPLER_MODULES = {
    "hhmm_tpu/infer/run.py": ("guard_update", "guard_where"),
    "hhmm_tpu/infer/chees.py": ("guard_update", "guard_where"),
    "hhmm_tpu/infer/gibbs.py": ("guard_update", "guard_where"),
}
GUARDS_MODULE = "hhmm_tpu.robust.guards"

# serving modules -> guard functions that must be imported from the
# named source modules AND called (invariant 3 in the module docstring)
SERVE_MODULES = {
    "hhmm_tpu/serve/online.py": ("safe_log_normalize",),
}
LMATH_MODULES = ("hhmm_tpu.core.lmath", "hhmm_tpu.core")

# time-parallel kernel modules: every semiring combine must be the
# guarded reduction (invariant 4 in the module docstring)
SEMIRING_MODULES = (
    "hhmm_tpu/kernels/semiring.py",
    "hhmm_tpu/kernels/assoc.py",
)
# attribute names whose access anywhere in a semiring module means a
# raw (unguarded) log-space reduction slipped in
RAW_LSE_ATTRS = ("logaddexp", "logsumexp")
# lmath helpers that WRAP the raw reduction (NaN cotangents on the
# all-(−inf) columns the −inf semiring identities create) — importing
# them into a semiring module is the loophole the attribute scan above
# cannot see
RAW_LSE_WRAPPERS = ("logsumexp", "log_vecmat", "log_matvec", "log_normalize")


def _bare_excepts(path: pathlib.Path, rel: str, problems: List[str]) -> None:
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append(f"{rel}:{node.lineno}: bare `except:` (name the exception types)")


def _imported_symbols(tree: ast.Module, modules) -> set:
    """Names bound from ``from <module> import ...`` for any of
    ``modules`` (package re-exports count too)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in modules:
            for alias in node.names:
                names.add(alias.asname or alias.name)
    return names


def _called_names(tree: ast.Module) -> set:
    calls = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            calls.add(node.func.id)
    return calls


def check(root: pathlib.Path) -> List[str]:
    problems: List[str] = []
    pkg = root / "hhmm_tpu"
    if not pkg.is_dir():
        return [f"{root}: no hhmm_tpu/ package to check"]
    for py in sorted(pkg.rglob("*.py")):
        _bare_excepts(py, str(py.relative_to(root)), problems)

    def check_guarded(spec, source_modules, kind, noun, what):
        for rel, guard_fns in sorted(spec.items()):
            path = root / rel
            if not path.is_file():
                problems.append(f"{rel}: {kind} module missing")
                continue
            tree = ast.parse(path.read_text(), filename=str(path))
            imported = _imported_symbols(tree, source_modules) & set(guard_fns)
            if not imported:
                problems.append(
                    f"{rel}: does not import a {noun} from {source_modules[0]} "
                    f"(expected one of {guard_fns})"
                )
                continue
            if not (imported & _called_names(tree)):
                problems.append(
                    f"{rel}: imports {sorted(imported)} but never calls it — "
                    f"{what}"
                )

    check_guarded(
        SAMPLER_MODULES,
        (GUARDS_MODULE, "hhmm_tpu.robust"),
        "sampler",
        "chain-health guard",
        "transitions are unguarded",
    )
    check_guarded(
        SERVE_MODULES,
        LMATH_MODULES,
        "serving",
        "guarded normalization",
        "the online step is unguarded",
    )

    # invariant 4: semiring combines use the guarded logsumexp only
    for rel in SEMIRING_MODULES:
        path = root / rel
        if not path.is_file():
            problems.append(f"{rel}: time-parallel kernel module missing")
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        imported = _imported_symbols(tree, LMATH_MODULES)
        if "safe_logsumexp" not in imported:
            problems.append(
                f"{rel}: does not import safe_logsumexp from "
                f"{LMATH_MODULES[0]} — semiring combines would be unguarded"
            )
        elif "safe_logsumexp" not in _called_names(tree):
            problems.append(
                f"{rel}: imports safe_logsumexp but never calls it — "
                "semiring combines are unguarded"
            )
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in RAW_LSE_ATTRS
            ):
                problems.append(
                    f"{rel}:{node.lineno}: raw `.{node.attr}` — semiring "
                    "combines must use the guarded safe_logsumexp from "
                    "hhmm_tpu.core.lmath"
                )
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if (
                        alias.name in RAW_LSE_ATTRS
                        and node.module not in LMATH_MODULES
                    ) or (
                        alias.name in RAW_LSE_WRAPPERS
                        and node.module in LMATH_MODULES
                    ):
                        problems.append(
                            f"{rel}:{node.lineno}: imports raw "
                            f"`{alias.name}` from {node.module} — use "
                            "safe_logsumexp from hhmm_tpu.core.lmath"
                        )
    return problems


def main(argv: List[str]) -> int:
    root = (
        pathlib.Path(argv[1])
        if len(argv) > 1
        else pathlib.Path(__file__).resolve().parent.parent
    )
    problems = check(root)
    for p in problems:
        print(p)
    if problems:
        print(f"check_guards: {len(problems)} violation(s)")
        return 1
    print(
        "check_guards: ok (no bare excepts; all samplers guarded; "
        "online serve step guarded; semiring combines guarded)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
