#!/usr/bin/env python
"""Static guard pass (tier-1, no JAX import) — thin shim over
``hhmm_tpu.analysis``.

Until PR 11 this file was a 773-line monolith of ten hand-written AST
invariants. Those invariants now live as first-class rules in the
:mod:`hhmm_tpu.analysis` rule engine (``hhmm_tpu/analysis/legacy.py``
— same detection logic, same messages, same scoping), alongside the
four new rule families the monolith could not express (hot-path
purity, PRNG discipline, dtype discipline, import layering — see
docs/static_analysis.md for the catalog, pragma syntax, and how to add
a rule).

This shim preserves the legacy contract exactly, so the tier-1 wiring
(tests/test_robust.py, test_serve.py, test_assoc.py, test_obs.py,
test_plan.py, test_profile.py, test_request.py) is untouched:

- ``python scripts/check_guards.py [root]`` — scan ``root`` (default:
  the repo), print one ``file[:line]: message`` line per violation.
- Exit 0 when clean (with the legacy ok summary line), 1 with a
  ``check_guards: N violation(s)`` tail otherwise.

New-rule ERROR findings print in the same stream (the legacy contract
is "N violation(s) == N printed lines", so warning-severity findings —
which never fail — stay out of this script entirely); suppressions
(inline ``# lint: ok <rule-id>`` pragmas and
``hhmm_tpu/analysis/allowlist.txt`` entries, both audited with
rationales) are honored. For warnings, per-finding rule ids, JSON
output, rule selection, and the rule catalog use the real CLI::

    python -m hhmm_tpu.analysis --format json hhmm_tpu/
    python -m hhmm_tpu.analysis --list-rules

``scripts/lint.py`` (or ``make lint``) is the pre-commit spelling.
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

_REPO = pathlib.Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from hhmm_tpu.analysis import run_analysis  # noqa: E402

_OK_LINE = (
    "check_guards: ok (no bare excepts; all samplers guarded; "
    "online serve step guarded; semiring combines guarded; "
    "monotonic clocks only; serve/bench jits telemetry-registered; "
    "one shared metrics plane; placement objects confined to the "
    "planner; serve hot paths degrade, never raise; timing loops "
    "confined to the obs/profile.py harness; serve-layer clocks "
    "confined to the obs/request.py plane; purity/PRNG/dtype/layering "
    "rule families clean — engine: hhmm_tpu.analysis)"
)


def main(argv: List[str]) -> int:
    root = (
        pathlib.Path(argv[1])
        if len(argv) > 1
        else pathlib.Path(__file__).resolve().parent.parent
    )
    if not (root / "hhmm_tpu").is_dir():
        print(f"{root}: no hhmm_tpu/ package to check")
        print("check_guards: 1 violation(s)")
        return 1
    report = run_analysis(root=root)
    # legacy line format (no rule-id bracket). ERROR findings only: the
    # legacy contract is "N violation(s) == N printed lines", so
    # warning-severity findings (which never fail) stay out of this
    # stream entirely — `python -m hhmm_tpu.analysis` shows them.
    errors = report.errors
    for f in errors:
        print(f.legacy_format())
    if errors:
        print(f"check_guards: {len(errors)} violation(s)")
        return 1
    print(_OK_LINE)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
