#!/usr/bin/env python
"""Static robustness pass (tier-1, no JAX import — pure ``ast``).

Asserts the two invariants the fault-tolerance subsystem
(`docs/robustness.md`) depends on:

1. **No bare ``except:``** anywhere under ``hhmm_tpu/`` — a bare handler
   swallows ``KeyboardInterrupt``/``SystemExit`` and, worse, masks the
   device faults the retry layer (`robust/retry.py`) must *see* to
   classify (UNAVAILABLE vs deterministic). Catch concrete types.
2. **Every public sampler entry point routes through the chain-health
   guard**: each sampler module (`infer/run.py`, `infer/chees.py`,
   `infer/gibbs.py`) must import from ``hhmm_tpu.robust.guards`` and
   actually *call* a guard function — a sampler added (or refactored)
   without the guard would silently reintroduce NaN poisoning of vmapped
   batches.

Exit 0 when clean, 1 with one line per violation. Run by
``tests/test_robust.py`` so the pass is enforced in tier-1.
"""

from __future__ import annotations

import ast
import pathlib
import sys
from typing import List

# sampler entry modules -> guard functions at least one of which must be
# both imported from hhmm_tpu.robust.guards and called
SAMPLER_MODULES = {
    "hhmm_tpu/infer/run.py": ("guard_update", "guard_where"),
    "hhmm_tpu/infer/chees.py": ("guard_update", "guard_where"),
    "hhmm_tpu/infer/gibbs.py": ("guard_update", "guard_where"),
}
GUARDS_MODULE = "hhmm_tpu.robust.guards"


def _bare_excepts(path: pathlib.Path, rel: str, problems: List[str]) -> None:
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append(f"{rel}:{node.lineno}: bare `except:` (name the exception types)")


def _guard_symbols(tree: ast.Module) -> set:
    """Names bound from ``from hhmm_tpu.robust.guards import ...`` (the
    robust package re-exports count too)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
            GUARDS_MODULE,
            "hhmm_tpu.robust",
        ):
            for alias in node.names:
                names.add(alias.asname or alias.name)
    return names


def _called_names(tree: ast.Module) -> set:
    calls = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            calls.add(node.func.id)
    return calls


def check(root: pathlib.Path) -> List[str]:
    problems: List[str] = []
    pkg = root / "hhmm_tpu"
    if not pkg.is_dir():
        return [f"{root}: no hhmm_tpu/ package to check"]
    for py in sorted(pkg.rglob("*.py")):
        _bare_excepts(py, str(py.relative_to(root)), problems)
    for rel, guard_fns in sorted(SAMPLER_MODULES.items()):
        path = root / rel
        if not path.is_file():
            problems.append(f"{rel}: sampler module missing")
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        imported = _guard_symbols(tree) & set(guard_fns)
        if not imported:
            problems.append(
                f"{rel}: does not import a chain-health guard from {GUARDS_MODULE} "
                f"(expected one of {guard_fns})"
            )
            continue
        if not (imported & _called_names(tree)):
            problems.append(
                f"{rel}: imports {sorted(imported)} but never calls a guard — "
                "transitions are unguarded"
            )
    return problems


def main(argv: List[str]) -> int:
    root = (
        pathlib.Path(argv[1])
        if len(argv) > 1
        else pathlib.Path(__file__).resolve().parent.parent
    )
    problems = check(root)
    for p in problems:
        print(p)
    if problems:
        print(f"check_guards: {len(problems)} violation(s)")
        return 1
    print("check_guards: ok (no bare excepts; all samplers guarded)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
