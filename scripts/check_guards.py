#!/usr/bin/env python
"""Static robustness pass (tier-1, no JAX import — pure ``ast``).

Asserts the invariants the fault-tolerance subsystem
(`docs/robustness.md`) and the streaming service (`docs/serving.md`)
depend on:

1. **No bare ``except:``** anywhere under ``hhmm_tpu/`` (the serving
   layer included) — a bare handler swallows
   ``KeyboardInterrupt``/``SystemExit`` and, worse, masks the device
   faults the retry layer (`robust/retry.py`) must *see* to classify
   (UNAVAILABLE vs deterministic). Catch concrete types.
2. **Every public sampler entry point routes through the chain-health
   guard**: each sampler module (`infer/run.py`, `infer/chees.py`,
   `infer/gibbs.py`) must import from ``hhmm_tpu.robust.guards`` and
   actually *call* a guard function — a sampler added (or refactored)
   without the guard would silently reintroduce NaN poisoning of vmapped
   batches.
3. **The online filter step routes through the guarded normalization**:
   ``serve/online.py`` must import ``safe_log_normalize`` from
   ``hhmm_tpu.core.lmath`` and call it — a streaming update normalized
   with a bare ``log_normalize`` would turn impossible evidence into
   NaN state instead of the −inf floor the scheduler's quarantine mask
   detects (`serve/scheduler.py`).
4. **Semiring combines use the guarded reduction**: the time-parallel
   kernels (`kernels/semiring.py`, `kernels/assoc.py`) must import
   ``safe_logsumexp`` from ``hhmm_tpu.core.lmath`` and call it, and
   must NOT touch any raw logsumexp — no ``jnp.logaddexp`` /
   ``jax.nn.logsumexp`` attribute access, no un-guarded ``logsumexp``
   import. Semiring *identity elements are −inf by construction*, so
   an all-identity fiber (masked run, impossible evidence) hits the
   all-(−inf) reduction edge case on every combine; a raw logsumexp
   there has NaN cotangents and, in naive forms, NaN values
   (docs/parallel_scan.md).
5. **Observability invariants** (`docs/observability.md`): (a) no raw
   ``time.time()`` call anywhere under ``hhmm_tpu/``, in ``bench.py``
   / ``bench_zoo.py``, or under ``scripts/`` — durations must come
   from the monotonic ``time.perf_counter()`` (directly or via the
   `hhmm_tpu/obs/trace.py` helpers); a wall-clock step (NTP slew,
   suspend/resume) under ``time.time()`` silently corrupts every
   throughput record built on it — and the ``scripts/tpu_*_probe.py``
   timings feed the measured crossover table `kernels/dispatch.py`
   bets real decode throughput on, so skew there corrupts dispatch
   decisions, not just records. (b) Every serve/bench module that
   creates a ``jax.jit`` entry point (``hhmm_tpu/serve/*.py``,
   ``bench.py``, ``bench_zoo.py``) must import a registration hook
   from ``hhmm_tpu.obs.telemetry`` and call it — otherwise run
   manifests lose per-entry-point compile attribution and the
   no-recompile audits go dark for that module.
6. **One metrics plane** (`hhmm_tpu/obs/metrics.py`): every module
   emitting health metrics goes through the shared registry — no
   private ``MetricsRegistry()`` instances outside ``obs/metrics.py``
   (a second registry forks the sink: its counters never reach the
   exports, manifests, or `scripts/obs_report.py`), no ad-hoc
   module-level count dicts, and any call to a bare
   ``counter``/``gauge``/``histogram`` name must be bound from the
   metrics module, not a local shadow.
7. **One placement substrate** (`hhmm_tpu/plan/`, `docs/sharding.md`):
   no ``Mesh`` / ``NamedSharding`` / ``PartitionSpec`` construction
   anywhere outside ``hhmm_tpu/plan/`` and the ``core/compat.py``
   shims — covering the package, ``bench.py`` / ``bench_zoo.py``,
   ``__graft_entry__.py``, and ``scripts/``. Before the planner,
   `batch/fit.py` and `serve/scheduler.py` each hand-rolled their own
   layout; a new callsite constructing placement objects directly
   would re-fragment the decision the planner exists to centralize
   (and its layout would be invisible to the manifest ``plan``
   stanza). Consumers take a ``Plan`` (or a caller mesh wrapped via
   ``plan_for_mesh``); kernel shard_map bodies describe specs through
   ``core.compat.pspec``.
8. **Serve hot paths degrade, never raise per-series**
   (`docs/serving.md` "Overload & failure modes"): in
   ``hhmm_tpu/serve/scheduler.py``, the hot-path entry points
   (``tick`` / ``flush`` / ``submit`` / ``attach*``) (a) contain no
   bare re-``raise`` — catching a per-series dispatch failure and
   re-propagating it is exactly the overload behavior the shed path
   exists to prevent — and (b) every ``self._dispatch(...)`` call
   inside them sits under a ``try`` whose handler catches ``Exception``
   (degrading the group into shed responses). A refactor that unwraps
   the dispatch would let one malformed observation (or a device loss)
   take down every other series' flush.
9. **One timing harness** (`hhmm_tpu/obs/profile.py`,
   `docs/observability.md` "kernel cost plane"): no raw
   ``perf_counter``-around-``block_until_ready`` timing loop anywhere
   under ``hhmm_tpu/`` outside ``obs/profile.py`` — the shape
   ``t0 = perf_counter(); for ...: block_until_ready(...); dt =
   perf_counter() - t0``. Every such loop re-derives the
   warmup/compile split, fresh-input, and order-statistic discipline
   by hand; device timings must come from ``obs.profile.device_time``
   so their numbers are comparable with the kernel cost DB rows
   dispatch bets on. Per-iteration clock reads inside the loop (phase
   *attribution*, e.g. `apps/tayal/wf.py`'s decode sub-profile) are
   fine — the flag is specifically a clocked batch of synced calls
   with no clock read per call. ``bench.py`` and the
   ``scripts/tpu_*_probe.py`` drivers are exempt (their timed loops
   are the measurement products themselves, and the probes now route
   through the harness anyway — migrated where trivial).
10. **Serve-layer clocks route through the request plane**
   (`hhmm_tpu/obs/request.py`, `docs/observability.md` "request
   plane"): no raw ``perf_counter`` read anywhere under
   ``hhmm_tpu/serve/`` — neither the bare imported name nor the
   ``time.perf_counter()`` / ``trace.perf_counter()`` attribute
   spelling. The serve hot paths used to sprinkle ad-hoc
   ``perf_counter`` deltas (one end-to-end stamp per tick); those all
   migrated into the per-tick lifecycle recorder
   (``TickTrace``/``RequestRecorder``), whose stamps decompose latency
   into queue/batch-formation/device/post-process shares per tenant. A
   new raw read in the serve layer would be a timing the request plane
   cannot see — route it through ``obs_request.now`` or a recorder
   stage stamp instead.

Exit 0 when clean, 1 with one line per violation. Run by
``tests/test_robust.py`` (and re-asserted by ``tests/test_serve.py``,
``tests/test_assoc.py``, and ``tests/test_obs.py``) so the pass is
enforced in tier-1.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys
from typing import List

# sampler entry modules -> guard functions at least one of which must be
# both imported from hhmm_tpu.robust.guards and called
SAMPLER_MODULES = {
    "hhmm_tpu/infer/run.py": ("guard_update", "guard_where"),
    "hhmm_tpu/infer/chees.py": ("guard_update", "guard_where"),
    "hhmm_tpu/infer/gibbs.py": ("guard_update", "guard_where"),
}
GUARDS_MODULE = "hhmm_tpu.robust.guards"

# serving modules -> guard functions that must be imported from the
# named source modules AND called (invariant 3 in the module docstring)
SERVE_MODULES = {
    "hhmm_tpu/serve/online.py": ("safe_log_normalize",),
}
LMATH_MODULES = ("hhmm_tpu.core.lmath", "hhmm_tpu.core")

# time-parallel kernel modules: every semiring combine must be the
# guarded reduction (invariant 4 in the module docstring)
SEMIRING_MODULES = (
    "hhmm_tpu/kernels/semiring.py",
    "hhmm_tpu/kernels/assoc.py",
)
# attribute names whose access anywhere in a semiring module means a
# raw (unguarded) log-space reduction slipped in
RAW_LSE_ATTRS = ("logaddexp", "logsumexp")
# lmath helpers that WRAP the raw reduction (NaN cotangents on the
# all-(−inf) columns the −inf semiring identities create) — importing
# them into a semiring module is the loophole the attribute scan above
# cannot see
RAW_LSE_WRAPPERS = ("logsumexp", "log_vecmat", "log_matvec", "log_normalize")

# invariant 5b: registration hooks a jax.jit-creating serve/bench module
# must import from the telemetry module and call. Only register_jit
# counts: install_listeners alone turns on the global compile listener
# without attributing any entry point, so accepting it would let a
# module's jits stay invisible to jit_cache_sizes()/run manifests —
# exactly the condition the invariant exists to prevent.
TELEMETRY_MODULES = ("hhmm_tpu.obs.telemetry", "hhmm_tpu.obs")
TELEMETRY_HOOKS = ("register_jit",)

# invariant 6: the shared statistical-health plane. Bare-name calls to
# these must be bound from the metrics module; a private registry or a
# module-level count dict forks the sink.
METRICS_MODULES = ("hhmm_tpu.obs.metrics", "hhmm_tpu.obs")
METRIC_FNS = ("counter", "gauge", "histogram")
AD_HOC_COUNT_RE = re.compile(r"(^|_)(counts?|counters?)$")

# invariant 7: placement-object constructors confined to the planner
# (and the core/compat.py shims) — any other construction site is a
# placement decision the planner cannot see or record
SHARDING_CTORS = ("Mesh", "NamedSharding", "PartitionSpec")
PLACEMENT_ALLOWED_PREFIXES = ("hhmm_tpu/plan/",)
PLACEMENT_ALLOWED_FILES = ("hhmm_tpu/core/compat.py",)

# invariant 8: the scheduler's hot-path entry points and the guarded
# per-group dispatch call they must wrap
SERVE_HOT_PATH_FILE = "hhmm_tpu/serve/scheduler.py"
HOT_PATH_METHOD_RE = re.compile(r"^(tick|flush|submit|attach\w*)$")
HOT_PATH_DISPATCH_ATTR = "_dispatch"

# invariant 9: raw timing loops confined to the profiling harness —
# the one module allowed to clock a batch of synced device calls
TIMING_HARNESS_FILE = "hhmm_tpu/obs/profile.py"

# invariant 10: the serve layer reads no raw clocks — every timing
# read under hhmm_tpu/serve/ routes through the request plane
# (hhmm_tpu/obs/request.py: `now` or a lifecycle recorder stamp)
SERVE_DIR_PREFIX = "hhmm_tpu/serve/"


def _bare_excepts(tree: ast.Module, rel: str, problems: List[str]) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append(f"{rel}:{node.lineno}: bare `except:` (name the exception types)")


def _imported_symbols(tree: ast.Module, modules) -> set:
    """Names bound from ``from <module> import ...`` for any of
    ``modules`` (package re-exports count too)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in modules:
            for alias in node.names:
                names.add(alias.asname or alias.name)
    return names


def _called_names(tree: ast.Module) -> set:
    calls = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            calls.add(node.func.id)
    return calls


def _check_raw_time(tree: ast.Module, rel: str, problems: List[str]) -> None:
    """Invariant 5a: flag every ``<time-module-alias>.time()`` call and
    every ``from time import time`` binding. ``perf_counter`` /
    ``monotonic`` reads (and the `obs/trace.py` helpers built on them)
    are the sanctioned clocks."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    problems.append(
                        f"{rel}:{node.lineno}: imports raw `time.time` — "
                        "use time.perf_counter (or hhmm_tpu.obs.trace)"
                    )
    if not aliases:
        return
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "time"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in aliases
        ):
            problems.append(
                f"{rel}:{node.lineno}: raw `{node.func.value.id}.time()` "
                "timing read — wall-clock steps corrupt throughput "
                "records; use time.perf_counter (or hhmm_tpu.obs.trace)"
            )


_JIT_MAKERS = ("jit", "pjit", "pmap")


def _uses_jax_jit(tree: ast.Module) -> bool:
    """True when the module creates jit entry points — either the
    attribute form (``jax.jit``/``jax.pjit``/``jax.pmap``) or names
    imported from jax (``from jax import jit``); both spellings must
    trip invariant 5b or the check is trivially evaded."""
    jitted_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
            "jax",
            "jax.experimental.pjit",
        ):
            for alias in node.names:
                if alias.name in _JIT_MAKERS:
                    jitted_names.add(alias.asname or alias.name)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and node.attr in _JIT_MAKERS
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
        ):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in jitted_names
        ):
            return True
    return False


def _check_telemetry_registration(
    tree: ast.Module, rel: str, problems: List[str]
) -> None:
    """Invariant 5b: a serve/bench module creating jax.jit entry points
    must import a telemetry hook (directly or via the telemetry module)
    and call it."""
    if not _uses_jax_jit(tree):
        return
    direct = _imported_symbols(tree, TELEMETRY_MODULES) & set(TELEMETRY_HOOKS)
    module_aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "hhmm_tpu.obs":
            for alias in node.names:
                if alias.name == "telemetry":
                    module_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "hhmm_tpu.obs.telemetry":
                    module_aliases.add(
                        alias.asname or "hhmm_tpu.obs.telemetry"
                    )
    called = bool(direct & _called_names(tree))
    if not called and module_aliases:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in TELEMETRY_HOOKS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in module_aliases
            ):
                called = True
                break
    if not (direct or module_aliases):
        problems.append(
            f"{rel}: creates jax.jit entry points but never imports a "
            f"telemetry hook from {TELEMETRY_MODULES[0]} (expected one "
            f"of {TELEMETRY_HOOKS}) — compile counts would be "
            "unattributable in run manifests"
        )
    elif not called:
        problems.append(
            f"{rel}: imports telemetry but never calls a registration "
            f"hook ({TELEMETRY_HOOKS}) — jit entry points are "
            "unregistered"
        )


def _check_metrics_discipline(
    tree: ast.Module, rel: str, problems: List[str]
) -> None:
    """Invariant 6: one shared metrics plane. (a) no private
    ``MetricsRegistry()`` outside ``obs/metrics.py``; (b) bare-name
    ``counter``/``gauge``/``histogram`` calls must be bound from the
    metrics module (a local shadow is an ad-hoc sink); (c) no
    module-level count-dict stores (``foo_counts = {}``) — counts that
    bypass the registry never reach the exports or obs_report."""
    if rel.replace("\\", "/") == "hhmm_tpu/obs/metrics.py":
        return
    imported = _imported_symbols(tree, METRICS_MODULES)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Name) and fn.id == "MetricsRegistry") or (
                isinstance(fn, ast.Attribute) and fn.attr == "MetricsRegistry"
            ):
                problems.append(
                    f"{rel}:{node.lineno}: instantiates a private "
                    "MetricsRegistry — a second registry forks the metrics "
                    "sink; use the shared hhmm_tpu.obs.metrics registry"
                )
            elif (
                isinstance(fn, ast.Name)
                and fn.id in METRIC_FNS
                and fn.id not in imported
            ):
                problems.append(
                    f"{rel}:{node.lineno}: calls bare `{fn.id}(...)` not "
                    "imported from hhmm_tpu.obs.metrics — ad-hoc metric "
                    "sinks never reach the exports/manifests/obs_report"
                )
    # (c) module-level count-dict assignments only (function-local
    # working dicts are algorithm state, not a metrics sink)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        is_dictish = isinstance(value, (ast.Dict, ast.DictComp)) or (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("dict", "defaultdict")
        )
        if not is_dictish:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and AD_HOC_COUNT_RE.search(t.id):
                problems.append(
                    f"{rel}:{node.lineno}: module-level count store "
                    f"`{t.id}` — route counts through the shared "
                    "hhmm_tpu.obs.metrics registry"
                )


def _check_placement_confinement(
    tree: ast.Module, rel: str, problems: List[str]
) -> None:
    """Invariant 7: flag every ``Mesh``/``NamedSharding``/
    ``PartitionSpec`` constructor call outside the allowed modules —
    both the bare-name spelling (``from jax.sharding import
    PartitionSpec as P; P(...)``) and the attribute spelling
    (``jax.sharding.Mesh(...)``)."""
    rel_n = rel.replace("\\", "/")
    if rel_n.startswith(PLACEMENT_ALLOWED_PREFIXES) or rel_n in PLACEMENT_ALLOWED_FILES:
        return
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax.sharding":
            for alias in node.names:
                if alias.name in SHARDING_CTORS:
                    aliases[alias.asname or alias.name] = alias.name
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        ctor = None
        if isinstance(fn, ast.Name) and fn.id in aliases:
            ctor = aliases[fn.id]
        elif isinstance(fn, ast.Attribute) and fn.attr in SHARDING_CTORS:
            ctor = fn.attr
        if ctor is not None:
            problems.append(
                f"{rel}:{node.lineno}: constructs `{ctor}` outside "
                "hhmm_tpu/plan/ — placement decisions belong to the "
                "execution planner (take a Plan / plan_for_mesh, or the "
                "core/compat.py pspec shim); see docs/sharding.md"
            )


def _handler_catches_exception(handler: ast.ExceptHandler) -> bool:
    """True when the handler's type covers ``Exception`` (bare handlers
    are already outlawed by invariant 1; BaseException would swallow
    KeyboardInterrupt and is not accepted as a degrade handler)."""
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return "Exception" in names


def _check_serve_hot_path(tree: ast.Module, rel: str, problems: List[str]) -> None:
    """Invariant 8: hot-path entry points (tick/flush/submit/attach*)
    in the scheduler (a) never bare-``raise`` (re-propagating a caught
    per-series failure) and (b) keep every ``self._dispatch(...)`` call
    under a try/except-``Exception`` degrade handler."""
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        for fn in [
            n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and HOT_PATH_METHOD_RE.match(n.name)
        ]:
            guarded_spans: List[Tuple[int, int]] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Raise) and node.exc is None:
                    problems.append(
                        f"{rel}:{node.lineno}: bare `raise` in serve hot path "
                        f"`{fn.name}` — per-series failures must degrade "
                        "into shed TickResponses, not propagate "
                        "(docs/serving.md overload ladder)"
                    )
                if isinstance(node, ast.Try) and any(
                    _handler_catches_exception(h) for h in node.handlers
                ):
                    lo = min(s.lineno for s in node.body)
                    hi = max(
                        getattr(s, "end_lineno", s.lineno) for s in node.body
                    )
                    guarded_spans.append((lo, hi))
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == HOT_PATH_DISPATCH_ATTR
                ):
                    if not any(
                        lo <= node.lineno <= hi for lo, hi in guarded_spans
                    ):
                        problems.append(
                            f"{rel}:{node.lineno}: `{HOT_PATH_DISPATCH_ATTR}` "
                            f"call in serve hot path `{fn.name}` outside a "
                            "try/except-Exception degrade handler — one "
                            "malformed observation or device loss would "
                            "fail every series in the flush"
                        )


def _perf_counter_names(tree: ast.Module) -> set:
    """Bare names bound to ``perf_counter`` (``from time import
    perf_counter``, ``from hhmm_tpu.obs.trace import perf_counter``,
    any alias) — the attribute spelling is matched structurally."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "perf_counter":
                    names.add(alias.asname or alias.name)
    return names


def _is_perf_counter_call(node: ast.AST, pc_names: set) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in pc_names:
        return True
    return isinstance(fn, ast.Attribute) and fn.attr == "perf_counter"


def _is_block_until_ready_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "block_until_ready":
        return True
    return isinstance(fn, ast.Attribute) and fn.attr == "block_until_ready"


def _own_scope_nodes(node: ast.AST) -> List[ast.AST]:
    """All descendants of ``node`` EXCLUDING nested function bodies —
    a nested def is its own timing scope (it is analyzed as its own
    function), so its clock reads and loops must not bleed into the
    enclosing function's line-number bracketing."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _check_timing_harness(tree: ast.Module, rel: str, problems: List[str]) -> None:
    """Invariant 9: flag every ``For``/``While`` loop that (a) syncs
    device work (``block_until_ready`` in its body), (b) reads no clock
    per iteration (so it is a timed BATCH, not per-call attribution),
    and (c) sits between a ``perf_counter`` read before it and one
    after it in the same function scope — the hand-rolled
    timing-harness shape that belongs in ``obs.profile.device_time``.
    Each function is analyzed over its OWN scope only (nested defs are
    separate scopes), so a loop is neither double-reported through its
    enclosing function nor bracketed by clock reads that never time
    it."""
    if rel.replace("\\", "/") == TIMING_HARNESS_FILE:
        return
    pc_names = _perf_counter_names(tree)
    fns = [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in fns:
        own = _own_scope_nodes(fn)
        pc_lines = [
            n.lineno for n in own if _is_perf_counter_call(n, pc_names)
        ]
        if len(pc_lines) < 2:
            continue
        for loop in own:
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            body_nodes = [
                n for s in loop.body for n in [s, *_own_scope_nodes(s)]
            ]
            if not any(_is_block_until_ready_call(n) for n in body_nodes):
                continue
            if any(_is_perf_counter_call(n, pc_names) for n in body_nodes):
                continue  # per-iteration clock read: attribution, fine
            end = getattr(loop, "end_lineno", loop.lineno)
            if any(l < loop.lineno for l in pc_lines) and any(
                l > end for l in pc_lines
            ):
                problems.append(
                    f"{rel}:{loop.lineno}: raw perf_counter-around-"
                    "block_until_ready timing loop — device timings "
                    "must go through hhmm_tpu.obs.profile.device_time "
                    "(the one harness with the warmup/compile split and "
                    "order-statistic discipline; see "
                    "docs/observability.md kernel cost plane)"
                )


def _check_serve_clock_confinement(
    tree: ast.Module, rel: str, problems: List[str]
) -> None:
    """Invariant 10: flag every ``perf_counter`` call under
    ``hhmm_tpu/serve/`` — the bare imported name and the attribute
    spelling both. The serve layer's clock reads belong to the
    request-plane lifecycle recorder (`hhmm_tpu/obs/request.py`), where
    per-tick stamps stay decomposable and tenant-attributable."""
    if not rel.replace("\\", "/").startswith(SERVE_DIR_PREFIX):
        return
    pc_names = _perf_counter_names(tree)
    for node in ast.walk(tree):
        if _is_perf_counter_call(node, pc_names):
            problems.append(
                f"{rel}:{node.lineno}: raw `perf_counter` read in the "
                "serve layer — per-tick timing must route through the "
                "request-plane lifecycle recorder (hhmm_tpu.obs.request "
                "`now`/stage stamps; see docs/observability.md request "
                "plane)"
            )


def check(root: pathlib.Path) -> List[str]:
    problems: List[str] = []
    pkg = root / "hhmm_tpu"
    if not pkg.is_dir():
        return [f"{root}: no hhmm_tpu/ package to check"]
    # one parse per package file, shared by every tree-walking invariant
    serve_dir = pkg / "serve"
    for py in sorted(pkg.rglob("*.py")):
        rel = str(py.relative_to(root))
        tree = ast.parse(py.read_text(), filename=str(py))
        _bare_excepts(tree, rel, problems)
        # invariant 5a: monotonic clocks only, package-wide
        _check_raw_time(tree, rel, problems)
        # invariant 6: one shared metrics plane, package-wide
        _check_metrics_discipline(tree, rel, problems)
        # invariant 7: placement objects only from the planner
        _check_placement_confinement(tree, rel, problems)
        # invariant 9: timing loops confined to the profiling harness
        _check_timing_harness(tree, rel, problems)
        # invariant 10: serve-layer clocks confined to the request plane
        _check_serve_clock_confinement(tree, rel, problems)
        # invariant 5b over the serving layer: every module with a
        # jax.jit entry point registers it with the telemetry registry
        if py.parent == serve_dir:
            _check_telemetry_registration(tree, rel, problems)
        # invariant 8: scheduler hot paths degrade, never raise
        if rel.replace("\\", "/") == SERVE_HOT_PATH_FILE:
            _check_serve_hot_path(tree, rel, problems)
    for bench_name in ("bench.py", "bench_zoo.py"):
        bench = root / bench_name
        if bench.is_file():
            btree = ast.parse(bench.read_text(), filename=str(bench))
            _check_raw_time(btree, bench_name, problems)
            _check_telemetry_registration(btree, bench_name, problems)
            _check_metrics_discipline(btree, bench_name, problems)
            _check_placement_confinement(btree, bench_name, problems)
    # __graft_entry__ hand-rolled the dryrun meshes before the planner;
    # invariant 7 keeps it a thin driver (5b does not apply: its jits
    # are one-shot dry-run probes, not serving entry points)
    graft = root / "__graft_entry__.py"
    if graft.is_file():
        gtree = ast.parse(graft.read_text(), filename=str(graft))
        _check_raw_time(gtree, "__graft_entry__.py", problems)
        _check_placement_confinement(gtree, "__graft_entry__.py", problems)
    # invariant 5a over scripts/: the tpu_*_probe timings feed the
    # measured crossover table kernels/dispatch.py dispatches on — a
    # wall-clock step there corrupts dispatch decisions silently
    # (invariant 7 rides along: a probe constructing its own mesh would
    # measure a layout the planner never dispatches)
    scripts_dir = root / "scripts"
    if scripts_dir.is_dir():
        for py in sorted(scripts_dir.glob("*.py")):
            stree = ast.parse(py.read_text(), filename=str(py))
            _check_raw_time(stree, f"scripts/{py.name}", problems)
            _check_placement_confinement(stree, f"scripts/{py.name}", problems)

    def check_guarded(spec, source_modules, kind, noun, what):
        for rel, guard_fns in sorted(spec.items()):
            path = root / rel
            if not path.is_file():
                problems.append(f"{rel}: {kind} module missing")
                continue
            tree = ast.parse(path.read_text(), filename=str(path))
            imported = _imported_symbols(tree, source_modules) & set(guard_fns)
            if not imported:
                problems.append(
                    f"{rel}: does not import a {noun} from {source_modules[0]} "
                    f"(expected one of {guard_fns})"
                )
                continue
            if not (imported & _called_names(tree)):
                problems.append(
                    f"{rel}: imports {sorted(imported)} but never calls it — "
                    f"{what}"
                )

    check_guarded(
        SAMPLER_MODULES,
        (GUARDS_MODULE, "hhmm_tpu.robust"),
        "sampler",
        "chain-health guard",
        "transitions are unguarded",
    )
    check_guarded(
        SERVE_MODULES,
        LMATH_MODULES,
        "serving",
        "guarded normalization",
        "the online step is unguarded",
    )

    # invariant 4: semiring combines use the guarded logsumexp only
    for rel in SEMIRING_MODULES:
        path = root / rel
        if not path.is_file():
            problems.append(f"{rel}: time-parallel kernel module missing")
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        imported = _imported_symbols(tree, LMATH_MODULES)
        if "safe_logsumexp" not in imported:
            problems.append(
                f"{rel}: does not import safe_logsumexp from "
                f"{LMATH_MODULES[0]} — semiring combines would be unguarded"
            )
        elif "safe_logsumexp" not in _called_names(tree):
            problems.append(
                f"{rel}: imports safe_logsumexp but never calls it — "
                "semiring combines are unguarded"
            )
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in RAW_LSE_ATTRS
            ):
                problems.append(
                    f"{rel}:{node.lineno}: raw `.{node.attr}` — semiring "
                    "combines must use the guarded safe_logsumexp from "
                    "hhmm_tpu.core.lmath"
                )
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if (
                        alias.name in RAW_LSE_ATTRS
                        and node.module not in LMATH_MODULES
                    ) or (
                        alias.name in RAW_LSE_WRAPPERS
                        and node.module in LMATH_MODULES
                    ):
                        problems.append(
                            f"{rel}:{node.lineno}: imports raw "
                            f"`{alias.name}` from {node.module} — use "
                            "safe_logsumexp from hhmm_tpu.core.lmath"
                        )
    return problems


def main(argv: List[str]) -> int:
    root = (
        pathlib.Path(argv[1])
        if len(argv) > 1
        else pathlib.Path(__file__).resolve().parent.parent
    )
    problems = check(root)
    for p in problems:
        print(p)
    if problems:
        print(f"check_guards: {len(problems)} violation(s)")
        return 1
    print(
        "check_guards: ok (no bare excepts; all samplers guarded; "
        "online serve step guarded; semiring combines guarded; "
        "monotonic clocks only; serve/bench jits telemetry-registered; "
        "one shared metrics plane; placement objects confined to the "
        "planner; serve hot paths degrade, never raise; timing loops "
        "confined to the obs/profile.py harness; serve-layer clocks "
        "confined to the obs/request.py plane)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
