"""On-device parity records for the blocked Pallas semiring kernel.

VERDICT r4 weak #6 / ask 8a: chunked-kernel parity was pinned only in
interpreter mode. This probe runs the real Mosaic-compiled kernels on
the TPU and records max-abs deviations against the XLA scan pair /
scan FFBS reference, writing `results/device_parity.json`.

Covers (all through the `kernels/dispatch.py` sanctioned entries —
the legacy pallas_* modules are deprecated shims):
- semiring_vg at the blocked schedule (ungated + gated) vs the vmapped
  scan vg at a long-T shape the dispatcher actually routes blocked
  (T=8192, K=4);
- semiring_ffbs at the single-block (resident) and blocked schedules
  (ungated + gated) vs ffbs_invcdf_reference given IDENTICAL
  uniforms — draws must be exactly equal, logliks close to f32
  reassociation.

Run on the axon tunnel (sole tunnel process). Wall target < 5 min.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

OUT = os.path.join(os.path.dirname(__file__), "..", "results", "device_parity.json")


def _mk(rng, B, T, K, masked_frac=0.1):
    log_pi = np.log(rng.dirichlet(np.ones(K), size=B))
    log_A = np.log(rng.dirichlet(np.ones(K), size=(B, K)))
    log_obs = rng.normal(size=(B, T, K)).astype(np.float32) - 1.0
    mask = np.ones((B, T), np.float32)
    # ragged tails, including one crossing a chunk boundary
    lens = rng.integers(int(T * (1 - masked_frac)), T + 1, size=B)
    for b, L in enumerate(lens):
        mask[b, L:] = 0.0
    gate = rng.integers(0, 2, size=(B, T)).astype(np.float32)
    skey = np.tile((np.arange(K) % 2).astype(np.float32), (B, 1))
    return (
        jnp.asarray(log_pi, jnp.float32),
        jnp.asarray(log_A, jnp.float32),
        jnp.asarray(log_obs),
        jnp.asarray(mask),
        jnp.asarray(gate),
        jnp.asarray(skey),
    )


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.default_rng(20260801)
    rec = {"device": str(jax.devices()[0]), "ts": time.strftime("%F %T")}

    B, T, K = 16, 8192, 4
    log_pi, log_A, log_obs, mask, gate, skey = _mk(rng, B, T, K)

    # ---- blocked vg vs scan pair (through the sanctioned dispatch
    # entries — analysis rule pallas-import) ----
    from hhmm_tpu.kernels.dispatch import semiring_vg
    from hhmm_tpu.kernels.vg import _vg_single, _vg_single_gated, chunk_for_k

    scan = jax.jit(jax.vmap(_vg_single))
    scan_g = jax.jit(jax.vmap(_vg_single_gated))
    chunked = jax.jit(
        lambda lp, lA, lo, m, *gate: semiring_vg(
            lp, lA, lo, m, *gate, t_block=chunk_for_k(K)
        )
    )

    for name, fs, fc, args in [
        ("vg_chunked", scan, chunked, (log_pi, log_A, log_obs, mask)),
        (
            "vg_chunked_gated",
            scan_g,
            chunked,
            (log_pi, log_A, log_obs, mask, gate, skey),
        ),
    ]:
        rs = [np.asarray(x) for x in fs(*args)]
        rc = [np.asarray(x) for x in fc(*args)]
        devs = {}
        for lbl, a, b in zip(("ll", "d_pi", "d_A", "d_obs"), rs, rc):
            devs[lbl] = float(np.max(np.abs(a - b)))
        # relative ll deviation on the O(1e3)-magnitude loglik
        devs["ll_rel"] = float(
            np.max(np.abs(rs[0] - rc[0]) / np.maximum(np.abs(rs[0]), 1.0))
        )
        rec[name] = {"shape": [B, T, K], **devs}
        print(name, devs, flush=True)

    # ---- FFBS: exact draw parity given identical uniforms ----
    from hhmm_tpu.kernels.dispatch import semiring_ffbs
    from hhmm_tpu.kernels.ffbs import ffbs_invcdf_reference

    def _resident(lp, lA, lo, m, u, *gate):
        return semiring_ffbs(lp, lA, lo, m, u, *gate, t_block=lo.shape[1])

    def _blocked(lp, lA, lo, m, u, *gate):
        return semiring_ffbs(lp, lA, lo, m, u, *gate, t_block=512)

    # single-block (resident, T*K <= 4096) and blocked schedules
    for name, Tr, fn, gated in [
        ("ffbs_resident", 1024, _resident, False),
        ("ffbs_resident_gated", 1024, _resident, True),
        ("ffbs_chunked", 8192, _blocked, False),
        ("ffbs_chunked_gated", 8192, _blocked, True),
    ]:
        lp, lA, lo, m, g, sk = _mk(rng, B, Tr, K)
        u = jnp.asarray(rng.uniform(size=(B, Tr)), jnp.float32)
        gargs = (g, sk) if gated else ()
        z_k, ll_k = jax.jit(fn)(lp, lA, lo, m, u, *gargs)
        z_r, ll_r = jax.jit(jax.vmap(ffbs_invcdf_reference))(
            *((lp, lA, lo, m, u) + gargs)
        )
        z_k, z_r = np.asarray(z_k), np.asarray(z_r)
        mismatch = int((z_k != z_r).sum())
        ll_dev = float(np.max(np.abs(np.asarray(ll_k) - np.asarray(ll_r))))
        rec[name] = {
            "shape": [B, Tr, K],
            "z_mismatch_steps": mismatch,
            "z_total_steps": int(z_k.size),
            "ll_maxdev": ll_dev,
        }
        print(name, rec[name], flush=True)

    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main()
