#!/usr/bin/env python
"""Measure the sequential-scan vs associative-scan crossover (K, T)
grid that `kernels/dispatch.py` dispatches on (mirrors
`tpu_pack2_probe.py`'s discipline: the dispatcher only adopts assoc
where this measurement says it wins).

Grid: K ∈ {2, 4, 8} × T ∈ {128, 256, 512, 1024, 2048, 4096}, three
kernels per point — forward filter, Viterbi, FFBS — and now the FULL
branch enum per kernel: seq, assoc, and (on TPU hardware, or with
``--pallas-interpret``) the blocked Pallas semiring branch, all
reached through the `kernels/dispatch.py` entries. Each is timed
twice: single-series jitted (the latency-bound decode path) and
vmapped over a B=64 batch (the throughput path; batching already
fills the machine, so the branch gaps shrink and the batched
crossover is the honest one for dispatch defaults). Fresh
pre-generated device inputs per timed call (host RNG + H2D outside
the window), ``block_until_ready`` + host reduction — the
tunnel-discipline rules of `tpu_pack2_probe.py`. A TPU run therefore
writes branch="pallas" rows next to seq/assoc at the same (K, T, B)
points and FLIPS three-way dispatch with zero code change.

Writes TWO artifacts from one measurement:

- **the kernel cost database** (`hhmm_tpu/obs/profile.py`,
  ``results/kernel_costs.json`` by default): every timed point lands
  as a (kernel, branch, K, T, B, dtype, device_kind, jax)-keyed row
  through the shared atomic writer — the rows `kernels/dispatch.py`
  reads as its measured crossover source. A run of this probe ON TPU
  HARDWARE therefore fills the empty TPU crossover directly: the next
  process on that device kind dispatches from the measurement, no
  table paste required.
- **`results/assoc_crossover.json`** (the human-readable note, kept):
  per-point ms/call for both branches plus the derived ``crossover``
  block — for each K, the smallest grid T where assoc wins both the
  batched filter and Viterbi — in the exact ``(K_max, T_min)`` row
  shape of ``kernels/dispatch.ASSOC_CROSSOVER``, ready to paste as
  the checked-in fallback for hosts without a DB.

All timing goes through the canonical ``device_time`` harness
(`obs/profile.py`: warmup/compile split, fresh pre-staged inputs,
``block_until_ready``, exact-order-statistic p50) — the discipline
this script used to hand-roll. Run with ``--cpu`` on a CI host or on
TPU hardware. Wall target < 4 min.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:  # runnable as `python scripts/tpu_assoc_probe.py`
    sys.path.insert(0, _ROOT)

OUT = os.path.join(
    os.path.dirname(__file__), "..", "results", "assoc_crossover.json"
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="probe the CPU backend")
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument(
        "--Ts", nargs="*", type=int, default=[128, 256, 512, 1024, 2048, 4096]
    )
    ap.add_argument("--Ks", nargs="*", type=int, default=[2, 4, 8])
    ap.add_argument(
        "--kernel-costs-out",
        default=None,
        metavar="PATH",
        help="kernel cost DB to write the measured rows into (default: "
        "results/kernel_costs.json, or $HHMM_TPU_KERNEL_COSTS)",
    )
    ap.add_argument(
        "--pallas-interpret",
        action="store_true",
        help="race the pallas branch on a non-TPU backend through the "
        "Pallas interpreter (plumbing smoke only — interpreter timings "
        "are not dispatch-grade, so pair this with a scratch "
        "--kernel-costs-out)",
    )
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    if not args.cpu:
        assert jax.default_backend() == "tpu", jax.default_backend()

    from hhmm_tpu.obs import profile as obs_profile

    backend = jax.default_backend()
    devices = jax.devices()
    device_kind = devices[0].device_kind if devices else None
    rng = np.random.default_rng(7)
    B, reps = args.batch, args.reps
    db = obs_profile.KernelCostDB(args.kernel_costs_out).load()

    def timed(fn, arg_sets):
        """Seconds/call through the canonical harness
        (`obs/profile.py` ``device_time``: compile on set -1, fresh
        pre-staged inputs per rep, ``block_until_ready``, monotonic
        clock — check_guards invariant 5a/9). Returns the full
        :class:`~hhmm_tpu.obs.profile.DeviceTiming` so the DB rows
        keep p50/min while the human-readable record keeps the mean
        (its historical field)."""
        return obs_profile.device_time(fn, arg_sets=arg_sets, reps=reps)

    # the SHARED measurement surface (obs/profile.py): this probe and
    # `bench.py --profile-kernels` write the same cost DB, so both must
    # measure the exact same computation per (kernel, branch) key
    inputs = lambda K, T, batch=None: obs_profile.dirichlet_hmm_inputs(
        rng, K, T, batch=batch
    )

    # stamped like a bench record (obs/manifest.py discipline): without
    # device_kind + jax versions a future TPU run could not land in the
    # dispatch-readable DB keyed on exactly those fields
    from hhmm_tpu.obs.manifest import stack_versions

    versions = stack_versions()
    rec = {
        "device": str(jax.devices()[0]),
        "backend": backend,
        "device_kind": device_kind,
        "jax_version": versions.get("jax"),
        "jaxlib_version": versions.get("jaxlib"),
        "ts": time.strftime("%F %T"),
        "reps": reps,
        "batch": B,
        "points": [],
    }
    kernels = obs_profile.decode_kernel_fns()
    # the pallas branch is raced on TPU hardware (the rows that flip
    # three-way dispatch); on other backends only under the explicit
    # interpreter smoke flag — interpreter wall time is not a device
    # measurement and the grid Ts would take minutes per point
    pallas_here = backend == "tpu" or args.pallas_interpret
    branch_names = ("seq", "assoc", "pallas") if pallas_here else ("seq", "assoc")
    rec["branches"] = list(branch_names)
    for K in args.Ks:
        for T in args.Ts:
            point = {"K": K, "T": T}
            for name, fns in kernels.items():
                for tag, batch in (("", None), ("_b", B)):
                    sets = [inputs(K, T, batch) for _ in range(reps + 1)]
                    jax.block_until_ready(sets)
                    timings = {}
                    for branch in branch_names:
                        fn = jax.jit(
                            jax.vmap(fns[branch]) if batch else fns[branch]
                        )
                        timings[branch] = timed(fn, sets)
                        point[f"{name}{tag}_{branch}_ms"] = round(
                            timings[branch].mean_s * 1e3, 3
                        )
                    point[f"{name}{tag}_speedup"] = round(
                        timings["seq"].mean_s / timings["assoc"].mean_s, 3
                    )
                    # the same measurement lands in the dispatch-readable
                    # cost DB (single series recorded as B=1)
                    for branch, timing in timings.items():
                        db.put_row(
                            kernel=name,
                            branch=branch,
                            K=K,
                            T=T,
                            B=batch or 1,
                            dtype="float32",
                            timing=timing,
                            device_kind=device_kind,
                            source="tpu_assoc_probe",
                        )
            rec["points"].append(point)
            # incremental atomic save: a mid-grid OOM/preemption (the
            # long-T assoc points are exactly where TPUs fall over)
            # must not discard the minutes of rows already measured
            db.save()
            print(json.dumps(point), flush=True)

    # derived dispatch rows: per K, smallest grid T where assoc wins
    # BOTH the batched filter and batched viterbi (the decode pair the
    # sweep gate tracks); None = never within the grid
    crossover = []
    for K in args.Ks:
        t_min = None
        for p in sorted(
            (p for p in rec["points"] if p["K"] == K), key=lambda p: p["T"]
        ):
            if p["filter_b_speedup"] > 1.0 and p["viterbi_b_speedup"] > 1.0:
                t_min = p["T"]
                break
        crossover.append({"K_max": K, "T_min": t_min})
    rec["crossover"] = {
        "rows": crossover,
        "note": "the kernel cost DB is now the dispatch source of truth "
        "for this device_kind (docs/parallel_scan.md runbook); "
        "optionally paste non-null rows into "
        f"kernels/dispatch.ASSOC_CROSSOVER[{backend!r}] as "
        "((K_max, T_min), ...) as the DB-less fallback",
    }
    print(json.dumps(rec["crossover"]))
    db.save()
    print(f"wrote {len(db.rows())} rows to {db.path}")
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main()
