#!/usr/bin/env python
"""Measure the sequential-scan vs associative-scan crossover (K, T)
grid that `kernels/dispatch.py` dispatches on (mirrors
`tpu_pack2_probe.py`'s discipline: the dispatcher only adopts assoc
where this measurement says it wins).

Grid: K ∈ {2, 4, 8} × T ∈ {128, 256, 512, 1024, 2048, 4096}, three
kernels per point — forward filter, Viterbi, FFBS — timed twice each:
single-series jitted (the latency-bound decode path) and vmapped over a
B=64 batch (the throughput path; batching already fills the machine, so
the assoc win shrinks and the batched crossover is the honest one for
dispatch defaults). Fresh pre-generated device inputs per timed call
(host RNG + H2D outside the window), ``block_until_ready`` + host
reduction — the tunnel-discipline rules of `tpu_pack2_probe.py`.

Writes `results/assoc_crossover.json`: per-point ms/call for both
branches plus a derived ``crossover`` block — for each K, the smallest
grid T where assoc wins both the filter and Viterbi timings (batched) —
in the exact ``(K_max, T_min)`` row shape of
``kernels/dispatch.ASSOC_CROSSOVER``, ready to paste. Run with
``--cpu`` on a CI host (records the cpu table) or on TPU hardware
(records the tpu table). Wall target < 4 min.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:  # runnable as `python scripts/tpu_assoc_probe.py`
    sys.path.insert(0, _ROOT)

OUT = os.path.join(
    os.path.dirname(__file__), "..", "results", "assoc_crossover.json"
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="probe the CPU backend")
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument(
        "--Ts", nargs="*", type=int, default=[128, 256, 512, 1024, 2048, 4096]
    )
    ap.add_argument("--Ks", nargs="*", type=int, default=[2, 4, 8])
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    if not args.cpu:
        assert jax.default_backend() == "tpu", jax.default_backend()

    from hhmm_tpu.kernels import (
        ffbs_assoc_sample,
        ffbs_fused,
        forward_filter,
        forward_filter_assoc,
        viterbi,
        viterbi_assoc,
    )

    backend = jax.default_backend()
    rng = np.random.default_rng(7)
    B, reps = args.batch, args.reps

    def timed(fn, arg_sets):
        """Mean seconds/call over ``reps`` calls with fresh inputs each
        (arg_sets pre-staged on device; compile on set -1)."""
        out = fn(*arg_sets[-1])
        jax.block_until_ready(out)
        # monotonic clock only (check_guards invariant 5a): a wall-clock
        # step here would corrupt the measured crossover table that
        # kernels/dispatch.py bets real decode throughput on
        t0 = time.perf_counter()
        for r in range(reps):
            jax.block_until_ready(fn(*arg_sets[r]))
        return (time.perf_counter() - t0) / reps

    def inputs(K, T, batch=None):
        shp = () if batch is None else (batch,)
        log_pi = jnp.asarray(
            np.log(rng.dirichlet(np.ones(K), shp or None)), jnp.float32
        )
        log_A = jnp.asarray(
            np.log(rng.dirichlet(np.ones(K), shp + (K,))), jnp.float32
        )
        log_obs = jnp.asarray(rng.normal(size=shp + (T, K)) - 1.0, jnp.float32)
        mask = jnp.ones(shp + (T,), jnp.float32)
        return log_pi, log_A, log_obs, mask

    rec = {
        "device": str(jax.devices()[0]),
        "backend": backend,
        "ts": time.strftime("%F %T"),
        "reps": reps,
        "batch": B,
        "points": [],
    }
    kernels = {
        "filter": (
            lambda lp, lA, lo, m: forward_filter(lp, lA, lo, m)[1],
            lambda lp, lA, lo, m: forward_filter_assoc(lp, lA, lo, m)[1],
        ),
        "viterbi": (
            lambda lp, lA, lo, m: viterbi(lp, lA, lo, m)[0],
            lambda lp, lA, lo, m: viterbi_assoc(lp, lA, lo, m)[0],
        ),
        "ffbs": (
            lambda lp, lA, lo, m: ffbs_fused(
                jax.random.PRNGKey(0), lp, lA, lo, m
            )[0],
            lambda lp, lA, lo, m: ffbs_assoc_sample(
                jax.random.PRNGKey(0), lp, lA, lo, m
            )[0],
        ),
    }
    for K in args.Ks:
        for T in args.Ts:
            point = {"K": K, "T": T}
            for name, (seq_fn, assoc_fn) in kernels.items():
                for tag, batch in (("", None), ("_b", B)):
                    sets = [inputs(K, T, batch) for _ in range(reps + 1)]
                    jax.block_until_ready(sets)
                    f_seq = jax.jit(
                        jax.vmap(seq_fn) if batch else seq_fn
                    )
                    f_assoc = jax.jit(
                        jax.vmap(assoc_fn) if batch else assoc_fn
                    )
                    t_seq = timed(f_seq, sets)
                    t_assoc = timed(f_assoc, sets)
                    point[f"{name}{tag}_seq_ms"] = round(t_seq * 1e3, 3)
                    point[f"{name}{tag}_assoc_ms"] = round(t_assoc * 1e3, 3)
                    point[f"{name}{tag}_speedup"] = round(t_seq / t_assoc, 3)
            rec["points"].append(point)
            print(json.dumps(point), flush=True)

    # derived dispatch rows: per K, smallest grid T where assoc wins
    # BOTH the batched filter and batched viterbi (the decode pair the
    # sweep gate tracks); None = never within the grid
    crossover = []
    for K in args.Ks:
        t_min = None
        for p in sorted(
            (p for p in rec["points"] if p["K"] == K), key=lambda p: p["T"]
        ):
            if p["filter_b_speedup"] > 1.0 and p["viterbi_b_speedup"] > 1.0:
                t_min = p["T"]
                break
        crossover.append({"K_max": K, "T_min": t_min})
    rec["crossover"] = {
        "rows": crossover,
        "note": "paste non-null rows into kernels/dispatch.ASSOC_CROSSOVER"
        f"[{backend!r}] as ((K_max, T_min), ...)",
    }
    print(json.dumps(rec["crossover"]))
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main()
