"""Producer for the registered stage's provenance seed-sensitivity arm.

Runs the reference-mimic chain (1 NUTS chain, 250 warmup + 250 draws,
`max_treedepth` 10, informed init — `tayal2009/main.R:34-39` budget) at
the registered seed 9400 plus the 4 sensitivity seeds, writing each
into the stage's ResultCache under the exact keys
`examples/tayal_replication.py::run_registered` reads
("registered-provenance-v1" / "registered-provenance-v1-seed"). All
seeds are recorded unconditionally — no outcome-dependent selection.

CPU-safe (forces the cpu platform before any jax op, so it never
touches the TPU tunnel): the mimic measures sampler provenance, and the
reference's own platform was CPU. ~2.5 min/seed.

Usage: python scripts/run_provenance_seeds.py CACHE_DIR
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")  # before any jax computation

import os  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

import time  # noqa: E402

import numpy as np  # noqa: E402

from tayal_replication import _load_gto_window, _relabeled_phis  # noqa: E402

from hhmm_tpu.apps.tayal.pipeline import run_window  # noqa: E402
from hhmm_tpu.batch import ResultCache, digest_key  # noqa: E402
from hhmm_tpu.infer import SamplerConfig  # noqa: E402
from hhmm_tpu.models import TayalHHMMLite  # noqa: E402


def main(cache_dir: str):
    cache = ResultCache(cache_dir)
    price, size, t, ins_end, span = _load_gto_window("rmd")
    model = TayalHHMMLite()
    cfg = SamplerConfig(
        num_warmup=250, num_samples=250, num_chains=1, max_treedepth=10
    )
    jobs = [(9400, {"stage": "registered-provenance-v1", "window": span})] + [
        (
            s,
            {
                "stage": "registered-provenance-v1-seed",
                "window": span,
                "seed": s,
            },
        )
        for s in (9401, 9402, 9403, 9404)
    ]
    for seed, keyspec in jobs:
        ck = digest_key(keyspec)
        if cache.get(ck) is not None:
            print(seed, "cached", flush=True)
            continue
        t0 = time.perf_counter()  # monotonic (check_guards invariant 5a)
        res = run_window(
            price, size, t, ins_end, config=cfg, key=jax.random.PRNGKey(seed)
        )
        _, pc, _ = _relabeled_phis(model, res, price, res.zig)
        hit = {
            "phi_45": np.array([pc[0]["phi_45"]]),
            "phi_25": np.array([pc[0]["phi_25"]]),
            "mean_logp": np.array([pc[0]["mean_logp"]]),
            "divergence_rate": np.array(
                [float(np.mean(res.stats.get("diverging", np.zeros(1))))]
            ),
        }
        cache.put(ck, hit)
        print(
            seed,
            round(time.perf_counter() - t0, 1),
            "s:",
            {k: round(float(v[0]), 4) for k, v in hit.items()},
            flush=True,
        )


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/hhmm_cache_r5")
