"""Jangmin (2004) driver — the replication the reference abandoned
(`hhmm/sim-jangmin2004.R`), completed: simulate the 5-regime market
tree, derive MA-gradient k-means labels from the price path, fit the
63-leaf hierarchy semi-supervised, and report regime decode quality
against the honest baselines (majority class, true-parameter oracle).

  python examples/jangmin_main.py --quick --cpu
"""

from __future__ import annotations

import sys, os
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import configure, standard_parser


def main() -> None:
    ap = standard_parser(__doc__)
    ap.add_argument("--T", type=int, default=300)
    ap.add_argument("--true-labels", action="store_true",
                    help="supervise with the simulated truth instead of k-means")
    args = ap.parse_args()
    cfg = configure(args)

    import jax

    from hhmm_tpu.apps.jangmin import fit_market, ma_gradient_labels, simulate_market

    rng = np.random.default_rng(args.seed)
    m = simulate_market(args.T, rng)
    g = m["regime"] if args.true_labels else ma_gradient_labels(m["price"])
    agree = (g == m["regime"]).mean()
    print(f"T={args.T}; label-vs-truth agreement {agree:.3f} "
          f"({'truth' if args.true_labels else 'MA-gradient k-means'})")

    fit = fit_market(m["x"], g, config=cfg, key=jax.random.PRNGKey(args.seed),
                     regime_true=m["regime"])
    div = float(np.asarray(fit.stats["diverging"]).mean())
    maj = np.bincount(m["regime"]).max() / len(m["regime"])
    print(f"divergence rate: {div:.4f}")
    print(f"unsupervised regime decode accuracy: {fit.accuracy:.3f} "
          f"(majority-class baseline {maj:.3f})")
    print("decoded regime counts:", np.bincount(fit.regime_hat, minlength=5))
    print("true regime counts:   ", np.bincount(m["regime"], minlength=5))


if __name__ == "__main__":
    main()
