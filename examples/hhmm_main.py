"""HHMM driver — the reference's `hhmm/main.R` (2×2 hierarchical
mixture) with the semisup fit its missing Stan file was meant to run:
build the tree, simulate from the recursive engine, fit the hierarchy
directly with TreeHMM, and report parameter + top-state recovery.

  python examples/hhmm_main.py
  python examples/hhmm_main.py --tree fine1998    # structure demo only
"""

from __future__ import annotations

import sys, os
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import configure, print_summary, run_sampler, standard_parser


def main() -> None:
    ap = standard_parser(__doc__)
    ap.add_argument("--tree", choices=("hier2x2", "fine1998"), default="hier2x2")
    ap.add_argument("--T", type=int, default=500)
    ap.add_argument("--unsup", action="store_true", help="drop the group labels")
    args = ap.parse_args()
    cfg = configure(args)

    import jax
    import jax.numpy as jnp

    from hhmm_tpu.hhmm.compile import compile_hhmm
    from hhmm_tpu.hhmm.examples import fine1998_tree, hier2x2_tree
    from hhmm_tpu.hhmm.simulate import hhmm_sim
    from hhmm_tpu.hhmm.structure import leaf_groups

    from hhmm_tpu.models import TreeHMM

    tree_fn = hier2x2_tree if args.tree == "hier2x2" else fine1998_tree
    tree = tree_fn()
    flat = compile_hhmm(tree)
    print(f"tree compiled: K={flat.K} leaves {flat.names}")
    print("flat pi:", np.round(flat.pi, 3))
    print("flat A:\n", np.round(flat.A, 3))

    rng = np.random.default_rng(args.seed)
    zleaf, x = hhmm_sim(tree, T=args.T, rng=rng)
    g = leaf_groups(tree)[zleaf]

    semisup = not args.unsup
    model = TreeHMM(tree_fn(), semisup=semisup, gate_mode="hard")
    data = {"x": jnp.asarray(x)}
    if semisup:
        data["g"] = jnp.asarray(g)
    from hhmm_tpu.infer import init_chains

    theta0 = init_chains(model, jax.random.PRNGKey(args.seed + 1), data, cfg.num_chains)
    qs, stats = run_sampler(
        None, jax.random.PRNGKey(args.seed + 2), theta0, cfg, vg_fn=model.make_vg(data)
    )
    print(f"divergence rate: {float(np.asarray(stats['diverging']).mean()):.4f}")
    print_summary(model.constrained_draws(qs), top=16)

    gen = model.generated(qs[:, :: max(1, cfg.num_samples // 50)], data)
    gamma = np.asarray(gen["gamma"]).mean(axis=(0, 1))
    top_hat = np.asarray(model.groups)[gamma.argmax(axis=1)]
    top_true = leaf_groups(tree)[zleaf]
    print(f"top-state recovery: {(top_hat == top_true).mean():.3f}")


if __name__ == "__main__":
    main()
