"""Batch backtest — the reference's `tayal2009/test-strategy.R`: build
rolling (train, trade) windows across symbols, fit every window in ONE
batched NUTS program, trade each with several lags, and aggregate.

  python examples/tayal_strategy.py                       # simulated
  python examples/tayal_strategy.py --ticks-dir DIR       # per-day CSVs
"""

from __future__ import annotations

import sys, os
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import configure, standard_parser


def main() -> None:
    ap = standard_parser(__doc__)
    ap.add_argument("--ticks-dir", default=None,
                    help="directory of per-day CSVs; subdirectories = symbols")
    ap.add_argument("--symbols", type=int, default=3, help="simulated symbols")
    ap.add_argument("--days", type=int, default=7, help="simulated days per symbol")
    ap.add_argument("--train-days", type=int, default=5)
    ap.add_argument("--legs-per-day", type=int, default=200)
    ap.add_argument("--lags", type=int, nargs="*", default=[0, 1, 2])
    ap.add_argument("--cache-dir", default=None)
    args = ap.parse_args()
    cfg = configure(args)

    import jax

    from hhmm_tpu.apps.tayal.wf import build_tasks, wf_trade

    if args.ticks_dir:
        from hhmm_tpu.apps.data_io import load_tick_days

        days = {
            name: load_tick_days(os.path.join(args.ticks_dir, name))
            for name in sorted(os.listdir(args.ticks_dir))
            if os.path.isdir(os.path.join(args.ticks_dir, name))
        }
        if not days:
            raise SystemExit(
                f"{args.ticks_dir}: no per-symbol subdirectories found "
                "(this script expects DIR/<symbol>/<day>.csv; for a flat "
                "directory of day CSVs use examples/tayal_main.py)"
            )
    else:
        from hhmm_tpu.apps.tayal.simulate import simulate_ticks

        days = {}
        for s in range(args.symbols):
            rng = np.random.default_rng(1000 * s + args.seed)
            sym_days = []
            for _ in range(args.days):
                price, size, tsec, _ = simulate_ticks(rng, n_legs=args.legs_per_day)
                sym_days.append({"price": price, "size": size, "t_seconds": tsec})
            days[f"SYM{s}"] = sym_days

    tasks = build_tasks(days, train_days=args.train_days, trade_days=1)
    print(f"{len(tasks)} (symbol, window) tasks")
    results = wf_trade(
        tasks,
        config=cfg,
        key=jax.random.PRNGKey(args.seed),
        lags=args.lags,
        cache_dir=args.cache_dir,
    )

    # aggregate daily returns per strategy (`tayal2009/main.Rmd:800`)
    print(f"{'symbol':<8}{'window':>7}{'div':>7}" + "".join(f"{f'lag{l}':>9}" for l in args.lags) + f"{'b&h':>9}")
    totals = {lag: [] for lag in args.lags}
    bnh_all = []
    for r in results:
        day_ret = {lag: 100 * np.sum(r.trades[lag].ret) for lag in args.lags}
        bnh = 100 * np.sum(r.bnh)
        for lag in args.lags:
            totals[lag].append(day_ret[lag])
        bnh_all.append(bnh)
        print(
            f"{r.symbol:<8}{r.window:>7}{r.diverged:>7.3f}"
            + "".join(f"{day_ret[lag]:>9.3f}" for lag in args.lags)
            + f"{bnh:>9.3f}"
        )
    print("-" * (22 + 9 * (len(args.lags) + 1)))
    print(
        f"{'mean':<22}" + "".join(f"{np.mean(totals[lag]):>9.3f}" for lag in args.lags)
        + f"{np.mean(bnh_all):>9.3f}"
    )


if __name__ == "__main__":
    main()
