"""Shared helpers for the example drivers (the reference's `main.R`
"Set up" + diagnostics blocks, `hmm/main.R:7-18,59-87`)."""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

# run from anywhere: the repo root precedes the examples dir on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def standard_parser(description: str) -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--warmup", type=int, default=300)
    ap.add_argument("--samples", type=int, default=300)
    ap.add_argument("--chains", type=int, default=2)
    ap.add_argument("--max-treedepth", type=int, default=6)
    ap.add_argument(
        "--sampler",
        choices=["nuts", "chees"],
        default="nuts",
        help="nuts (default; Stan semantics) or chees — cross-chain "
        "adaptive HMC (hhmm_tpu/infer/chees.py), needs chains >= 2",
    )
    ap.add_argument("--max-leapfrogs", type=int, default=32, help="ChEES leapfrog cap")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu", action="store_true", help="force the CPU backend")
    ap.add_argument(
        "--quick", action="store_true", help="tiny budgets for smoke runs"
    )
    ap.add_argument(
        "--plots-dir",
        default=None,
        help="write diagnostic PNGs here (default: no plots)",
    )
    return ap


def configure(args):
    """Apply --cpu/--quick and return a SamplerConfig or ChEESConfig
    (per --sampler; fit_batched and run_sampler dispatch on the type)."""
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.quick:
        args.warmup, args.samples, args.chains = 50, 50, 1
    from hhmm_tpu.infer import ChEESConfig, SamplerConfig

    if getattr(args, "sampler", "nuts") == "chees":
        return ChEESConfig(
            num_warmup=args.warmup,
            num_samples=args.samples,
            num_chains=max(2, args.chains),  # cross-chain adaptation
            max_leapfrogs=args.max_leapfrogs,
        )
    return SamplerConfig(
        num_warmup=args.warmup,
        num_samples=args.samples,
        num_chains=args.chains,
        max_treedepth=args.max_treedepth,
    )


def run_sampler(logp_fn, key, init_q, config, vg_fn=None):
    """Alias for :func:`hhmm_tpu.infer.sample` (config-type dispatch)."""
    from hhmm_tpu.infer import sample

    return sample(logp_fn, key, init_q, config, vg_fn=vg_fn)


def print_summary(samples: dict, top: int = 12) -> None:
    """The drivers' `summary(stan.fit)` table."""
    from hhmm_tpu.infer import summary

    table = summary(samples)
    print(f"{'param':<18}{'mean':>9}{'sd':>9}{'2.5%':>9}{'50%':>9}{'97.5%':>9}{'n_eff':>8}{'Rhat':>7}")
    shown = 0
    for name, st in table.items():
        means = np.atleast_1d(st["mean"])
        for i in range(means.shape[0]):
            if shown >= top:
                print(f"... ({sum(np.atleast_1d(s['mean']).size for s in table.values())} scalars total)")
                return
            label = name if means.shape[0] == 1 else f"{name}[{i}]"
            print(
                f"{label:<18}"
                f"{np.atleast_1d(st['mean'])[i]:>9.3f}{np.atleast_1d(st['sd'])[i]:>9.3f}"
                f"{np.atleast_1d(st['q2.5'])[i]:>9.3f}{np.atleast_1d(st['q50'])[i]:>9.3f}"
                f"{np.atleast_1d(st['q97.5'])[i]:>9.3f}"
                f"{np.atleast_1d(st['n_eff'])[i]:>8.0f}{np.atleast_1d(st['rhat'])[i]:>7.3f}"
            )
            shown += 1


def save_figure(fig, plots_dir: str | None, name: str) -> None:
    if plots_dir is None:
        return
    os.makedirs(plots_dir, exist_ok=True)
    path = os.path.join(plots_dir, name)
    fig.savefig(path, dpi=110, bbox_inches="tight")
    print(f"wrote {path}")
