"""Replicate Tayal (2009) on the REAL TSX tick data.

Two stages, matching the reference drivers:

- ``single``: the G.TO window of `tayal2009/main.R:15-58` — 5 in-sample
  days (2007-05-01..07) + 1 OOS day (05-08), fit the lite model, and
  compare the posterior emission spot-checks against the write-up's
  published values φ̂₄₅ = 0.88, φ̂₂₅ = 0.80 (`tayal2009/main.Rmd:560`).
- ``wf``: the full walk-forward backtest of `tayal2009/test-strategy.R:
  44-61` — 12 tickers × rolling 5-day-train/1-day-trade windows, all
  fits as ONE batched TPU program, recording the per-strategy daily
  return table (1,428 returns in the reference, `main.Rmd:800`).

Results land in ``results/tayal_replication.json``.

Run from the repo root (the TPU tunnel only registers there)::

    python examples/tayal_replication.py single
    python examples/tayal_replication.py wf
"""

from __future__ import annotations

import argparse
import datetime as dt
import json
import os
import sys
from typing import Dict, List

import numpy as np

# run from anywhere: the repo root precedes the examples dir on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA_ROOT = "/root/reference/tayal2009/data"
RESULTS = os.path.join(os.path.dirname(__file__), "..", "results")

# published values this replication is checked against
PUBLISHED = {"phi_45": 0.88, "phi_25": 0.80}

# UTC epoch seconds for local (America/Toronto, EDT = UTC-4 in May 2007)
def _toronto(y, m, d, hh, mm):
    return (
        dt.datetime(y, m, d, hh, mm, tzinfo=dt.timezone(dt.timedelta(hours=-4)))
        .timestamp()
    )


def _phi_draws(model, samples: np.ndarray) -> np.ndarray:
    """Posterior draws of the emission matrix, [draws, K, L]."""
    import jax
    import jax.numpy as jnp

    flat = np.asarray(samples).reshape(-1, np.asarray(samples).shape[-1])
    unpack = jax.jit(jax.vmap(lambda q: model.unpack(q)[0]["phi_k"]))
    return np.asarray(unpack(jnp.asarray(flat)))


# bear/bull pair swap, preserving up/down roles: canonical pair {0,1} =
# bear (0 down-leg, 1 up-leg), {2,3} = bull (2 up, 3 down)
_PAIR_SWAP = np.array([3, 2, 1, 0])


def _canonical_phi_per_chain(model, res, price, zig) -> Dict:
    """Pool emission draws across chains AFTER per-chain ex-post
    relabeling: the pair-swap symmetry (p11 <-> 1-p11 etc.) is a true
    posterior mode pair, and chains land in either mode — averaging raw
    draws across chains mixes the modes and shrinks φ̂ toward 0.5. The
    reference relabels its single chain by mean-return ordering
    (`tayal2009/main.R:176-184`); we apply that rule chain-wise."""
    from hhmm_tpu.apps.tayal.analytics import (
        map_to_topstate,
        relabel_by_return,
        topstate_runs,
    )
    from hhmm_tpu.apps.tayal.features import to_model_inputs
    from hhmm_tpu.apps.tayal.pipeline import decode_states
    import jax.numpy as jnp

    x, sign = to_model_inputs(zig.feature)
    n_ins = res.n_ins_legs
    data = {
        "x": jnp.asarray(x[:n_ins]),
        "sign": jnp.asarray(sign[:n_ins]),
        "x_oos": jnp.asarray(x[n_ins:]),
        "sign_oos": jnp.asarray(sign[n_ins:]),
    }
    chains = res.samples.shape[0]
    logp = np.asarray(res.stats["logp"])  # [chains, draws]
    chain_lp = logp.mean(axis=1)
    phis, per_chain = [], []
    for c in range(chains):
        leg_state = decode_states(model, res.samples[c], data, n_thin=40)
        top = map_to_topstate(leg_state)
        runs = topstate_runs(top, zig.start, zig.end, np.asarray(price))
        _, _, swapped = relabel_by_return(runs, top)
        phi_c = _phi_draws(model, res.samples[c])  # [draws, 4, 9]
        if swapped:
            phi_c = phi_c[:, _PAIR_SWAP, :]
        phis.append(phi_c)
        per_chain.append(
            {"swapped": bool(swapped), "phi_45": float(phi_c[:, 3, 4].mean()),
             "phi_25": float(phi_c[:, 1, 4].mean()),
             "mean_logp": float(chain_lp[c])}
        )
    # mode selection: the posterior is multimodal beyond the exact pair
    # symmetry (minor modes swap emission structure within a pair);
    # chains stuck in dominated modes would bias the pooled estimate, so
    # pool only chains whose mean log-density reaches the best chain's
    # (within a few nats — the reference's single Stan chain reports the
    # dominant mode it lands in)
    keep = chain_lp >= chain_lp.max() - 10.0
    phi = np.concatenate([p for p, k in zip(phis, keep) if k])
    return {"phi": phi, "per_chain": per_chain,
            "chains_pooled": int(keep.sum()), "chain_mean_logp": chain_lp.tolist()}


def spot_checks(phi_mean: np.ndarray) -> Dict[str, float]:
    """The write-up's φ̂₄₅/φ̂₂₅ on canonically-labeled states: φ̂₄₅ is
    the bull-pair down-leg state at symbol 5 (canonical state 3);
    φ̂₂₅ the bear-pair up-leg state (canonical state 1)."""
    return {
        "phi_45": float(phi_mean[3, 4]),
        "phi_25": float(phi_mean[1, 4]),
    }


def _sampler_config(args):
    """ChEES by default: bounded leapfrogs keep each device dispatch
    short (the tunnel kills single XLA programs that run >~10 min —
    NUTS at depth 7-8 on a ~10k-leg real window exceeds that). Gibbs
    (hard gate — identical on strictly alternating zig-zag signs) is
    the fast path for the walk-forward backtest."""
    from hhmm_tpu.infer import ChEESConfig, GibbsConfig, SamplerConfig

    if args.sampler == "nuts":
        return SamplerConfig(
            num_warmup=args.warmup,
            num_samples=args.samples,
            num_chains=args.chains,
            max_treedepth=args.max_treedepth,
        )
    if args.sampler == "gibbs":
        return GibbsConfig(
            num_warmup=args.warmup,
            num_samples=args.samples,
            num_chains=args.chains,
        )
    return ChEESConfig(
        num_warmup=args.warmup,
        num_samples=args.samples,
        num_chains=max(2, args.chains),
        max_leapfrogs=args.max_leapfrogs,
    )


def run_single(args) -> Dict:
    import jax
    from hhmm_tpu.apps.rdata import load_tick_days_rdata
    from hhmm_tpu.apps.tayal.pipeline import run_window

    days = load_tick_days_rdata(os.path.join(DATA_ROOT, "G.TO"), days=6)
    price = np.concatenate([d["price"] for d in days])
    size = np.concatenate([d["size"] for d in days])
    t = np.concatenate([d["t_seconds"] for d in days])
    # in-sample boundary: 2007-05-07 16:30 America/Toronto
    # (`tayal2009/main.R:23`)
    ins_end = int(np.searchsorted(t, _toronto(2007, 5, 7, 16, 30), "right")) - 1

    cfg = _sampler_config(args)
    res = run_window(
        price, size, t, ins_end, config=cfg, key=jax.random.PRNGKey(args.seed)
    )
    from hhmm_tpu.models import TayalHHMMLite

    canon = _canonical_phi_per_chain(TayalHHMMLite(), res, price, res.zig)
    phi = canon["phi"]
    checks = spot_checks(phi.mean(axis=0))
    checks["per_chain"] = canon["per_chain"]
    checks["chains_pooled"] = canon["chains_pooled"]
    checks["chain_mean_logp"] = canon["chain_mean_logp"]
    out = {
        "config": {
            "ticker": "G.TO",
            "days": "2007-05-01..2007-05-08",
            "n_ticks": int(len(price)),
            "n_legs": int(len(res.zig)),
            "n_ins_legs": int(res.n_ins_legs),
            "warmup": args.warmup,
            "samples": args.samples,
            "chains": args.chains,
            "sampler": args.sampler,
            "seed": args.seed,
        },
        "published": PUBLISHED,
        "replicated": checks,
        "abs_error": {
            k: abs(checks[k] - PUBLISHED[k]) for k in PUBLISHED
        },
        "phi_mean": phi.mean(axis=0).round(4).tolist(),
        "phi_sd": phi.std(axis=0).round(4).tolist(),
        "swapped": bool(res.swapped),
        "divergence_rate": float(np.mean(res.stats.get("diverging", np.zeros(1)))),
        "summary": res.summary,
        "oos_trades_lag1": {
            "n_trades": int(len(res.trades[1].ret)),
            "total_return_pct": float(np.sum(res.trades[1].ret) * 100),
        },
        "oos_buyhold_return_pct": float(np.sum(res.bnh) * 100),
    }
    return out


def run_wf(args) -> Dict:
    import jax
    from hhmm_tpu.apps.rdata import load_tick_days_rdata
    from hhmm_tpu.apps.tayal.wf import build_tasks, wf_trade

    symbols = sorted(
        d for d in os.listdir(DATA_ROOT)
        if os.path.isdir(os.path.join(DATA_ROOT, d))
    )
    if args.symbols:
        symbols = [s for s in symbols if s in args.symbols.split(",")]
    days = {
        sym: load_tick_days_rdata(os.path.join(DATA_ROOT, sym))
        for sym in symbols
    }
    tasks = build_tasks(days, train_days=5, trade_days=1)
    if args.max_tasks:
        tasks = tasks[: args.max_tasks]
    cfg = _sampler_config(args)
    results = wf_trade(
        tasks,
        config=cfg,
        key=jax.random.PRNGKey(args.seed),
        chunk_size=args.chunk,
        cache_dir=args.cache_dir,
        # conjugate Gibbs needs the exact-HMM factorization; identical
        # posterior on strictly-alternating zig-zag signs
        gate_mode="hard" if args.sampler == "gibbs" else "stan",
    )

    # per-strategy daily-return table (`main.Rmd:800`: one return per
    # (task, strategy); strategies = buy&hold + lags 0..5)
    lags = sorted(results[0].trades)
    table: List[Dict] = []
    for r in results:
        row = {
            "symbol": r.symbol,
            "window": r.window,
            "bnh_pct": float(np.sum(r.bnh) * 100),
            "diverged": r.diverged,
        }
        for lag in lags:
            row[f"lag{lag}_pct"] = float(np.sum(r.trades[lag].ret) * 100)
            row[f"lag{lag}_trades"] = int(len(r.trades[lag].ret))
        table.append(row)

    def _col(name):
        return np.array([row[name] for row in table])

    strategies = {"bnh": _col("bnh_pct")}
    for lag in lags:
        strategies[f"lag{lag}"] = _col(f"lag{lag}_pct")
    agg = {
        name: {
            "mean_daily_pct": float(v.mean()),
            "sd_daily_pct": float(v.std()),
            "total_pct": float(v.sum()),
            "hit_rate": float((v > 0).mean()),
            "n": int(v.size),
        }
        for name, v in strategies.items()
    }
    return {
        "config": {
            "symbols": symbols,
            "n_tasks": len(tasks),
            "n_returns": len(tasks) * (len(lags) + 1),
            "warmup": args.warmup,
            "samples": args.samples,
            "chains": args.chains,
            "chunk": args.chunk,
            "seed": args.seed,
        },
        "reference_volume": "12 stocks x ~17 windows x 7 strategies = 1428 returns (`tayal2009/main.Rmd:800`)",
        "aggregate": agg,
        "per_window": table,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("stage", choices=["single", "wf"])
    ap.add_argument("--warmup", type=int, default=250)
    ap.add_argument("--samples", type=int, default=250)
    ap.add_argument("--chains", type=int, default=4)
    ap.add_argument("--max-treedepth", type=int, default=8)
    ap.add_argument("--max-leapfrogs", type=int, default=32)
    ap.add_argument("--sampler", choices=["chees", "nuts", "gibbs"], default="chees")
    ap.add_argument("--seed", type=int, default=9000)
    ap.add_argument("--chunk", type=int, default=64)
    ap.add_argument("--symbols", type=str, default="")
    ap.add_argument("--max-tasks", type=int, default=0)
    ap.add_argument("--cache-dir", type=str, default=None)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()
    if args.stage == "single" and args.sampler == "gibbs":
        raise SystemExit(
            "--sampler gibbs is walk-forward only (run_window samples "
            "through the density-based API); use 'wf', or chees/nuts "
            "for the single stage"
        )

    out = run_single(args) if args.stage == "single" else run_wf(args)
    os.makedirs(RESULTS, exist_ok=True)
    path = args.out or os.path.join(RESULTS, "tayal_replication.json")
    merged = {}
    if os.path.exists(path):
        with open(path) as f:
            merged = json.load(f)
    merged[args.stage] = out
    with open(path, "w") as f:
        json.dump(merged, f, indent=1)
    print(json.dumps({args.stage: out.get("replicated", out.get("aggregate"))}, indent=1))
    print("wrote", os.path.abspath(path))


if __name__ == "__main__":
    main()
