"""Replicate Tayal (2009) on the REAL TSX tick data.

Two stages, matching the reference drivers:

- ``single``: the G.TO window of `tayal2009/main.R:15-58` — 5 in-sample
  days (2007-05-01..07) + 1 OOS day (05-08), fit the lite model, and
  compare the posterior emission spot-checks against the write-up's
  published values φ̂₄₅ = 0.88, φ̂₂₅ = 0.80 (`tayal2009/main.Rmd:560`).
- ``wf``: the full walk-forward backtest of `tayal2009/test-strategy.R:
  44-61` — 12 tickers × rolling 5-day-train/1-day-trade windows, all
  fits as ONE batched TPU program, recording the per-strategy daily
  return table (1,428 returns in the reference, `main.Rmd:800`).

Results land in ``results/tayal_replication.json``.

Run from the repo root (the TPU tunnel only registers there)::

    python examples/tayal_replication.py single
    python examples/tayal_replication.py wf
"""

from __future__ import annotations

import argparse
import datetime as dt
import json
import os
import sys
from typing import Dict, List

import numpy as np

# run from anywhere: the repo root precedes the examples dir on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA_ROOT = "/root/reference/tayal2009/data"
RESULTS = os.path.join(os.path.dirname(__file__), "..", "results")

# published values this replication is checked against
# (`tayal2009/main.Rmd:560` region; main.pdf §3.6.2 — the single-window
# study is the Rmd's 2007-05-04..10 window, OOS 05-11)
PUBLISHED = {"phi_45": 0.88, "phi_25": 0.80}

# main.pdf Table 5: G.TO compound daily returns (%), columns
# [buy&hold, lag0..lag5], one row per OOS trading day 05-08..05-31
PUBLISHED_T5_DAYS = [
    "2007-05-08", "2007-05-09", "2007-05-10", "2007-05-11", "2007-05-14",
    "2007-05-15", "2007-05-16", "2007-05-17", "2007-05-18", "2007-05-22",
    "2007-05-23", "2007-05-24", "2007-05-25", "2007-05-28", "2007-05-29",
    "2007-05-30", "2007-05-31",
]
PUBLISHED_T5 = {
    "2007-05-08": [-1.24, 3.99, -1.52, -0.92, 0.70, 0.62, 1.74],
    "2007-05-09": [-0.41, 3.93, 0.33, 1.82, 1.89, 0.55, 0.77],
    "2007-05-10": [-0.37, 4.19, 1.18, -0.61, 1.81, 1.73, 0.18],
    "2007-05-11": [-0.04, 0.18, 0.10, 1.13, -0.50, -0.64, 0.29],
    "2007-05-14": [-3.33, 2.71, -1.33, 0.63, -0.95, -0.46, -1.20],
    "2007-05-15": [-0.04, 3.48, -0.16, 0.06, 1.83, 2.06, 0.12],
    "2007-05-16": [-0.42, 5.45, -0.78, -0.38, 1.23, 2.80, -2.38],
    "2007-05-17": [-0.12, -1.78, 0.09, 2.41, 0.42, -2.97, -0.25],
    "2007-05-18": [1.25, -1.02, 0.70, 0.38, 2.20, 1.41, 1.73],
    "2007-05-22": [-2.39, -1.92, -1.89, 1.70, 0.52, 2.39, 2.16],
    "2007-05-23": [-1.02, 1.72, -0.11, -0.65, -0.73, 0.96, 1.45],
    "2007-05-24": [-3.18, 2.45, -0.25, -0.92, -0.74, -0.00, -1.61],
    "2007-05-25": [0.33, -1.36, -1.44, 0.06, 0.69, -1.83, -1.72],
    "2007-05-28": [-0.81, -1.79, -0.90, 0.65, -1.42, -1.01, 1.51],
    "2007-05-29": [-2.25, -1.53, -1.30, -1.80, -0.10, 2.12, 0.97],
    "2007-05-30": [1.41, -2.21, -3.49, -3.10, -1.25, -3.88, -2.30],
    "2007-05-31": [3.96, 0.20, -1.32, -0.86, -1.70, -1.33, -2.97],
}
# main.pdf Table 6: aggregate summary over all 12x17 daily compound
# returns (%), columns [buy&hold, lag0..lag5]
PUBLISHED_T6 = {
    "min": [-4.51, -21.54, -42.41, -25.71, -7.47, -5.76, -6.09],
    "mean": [-0.01, -0.18, -0.95, -0.17, 0.30, 0.44, 0.45],
    "median": [-0.02, 0.07, -0.29, -0.00, 0.22, 0.35, 0.46],
    "max": [5.82, 20.56, 12.90, 11.27, 8.08, 8.23, 5.71],
    "sd": [1.69, 4.11, 4.71, 3.25, 2.08, 1.95, 1.89],
    "iqr": [1.96, 3.70, 3.11, 3.12, 2.56, 2.76, 2.25],
}
# main.pdf Tables 9-20 "Total" rows: per-stock 17-day compound total
# (fractions, 2dp), columns [buy&hold, lag0..lag5]
PUBLISHED_STOCK_TOTALS = {
    "BBDb.TO": [-0.01, -0.73, -0.87, -0.49, -0.17, 0.03, 0.34],
    "BCE.TO": [0.08, -0.31, -0.12, -0.07, -0.09, -0.02, 0.02],
    "CTCa.TO": [0.06, 0.13, 0.03, 0.07, 0.13, 0.15, 0.21],
    "ECA.TO": [0.06, 0.24, 0.09, 0.05, 0.12, 0.03, 0.04],
    "G.TO": [-0.09, 0.17, -0.12, -0.01, 0.04, 0.02, -0.02],
    "K.TO": [-0.10, -0.11, -0.22, -0.07, 0.14, 0.12, 0.03],
    "MGa.TO": [0.07, 0.48, 0.45, 0.38, 0.32, 0.11, 0.07],
    "NXY.TO": [-0.07, 0.01, -0.16, -0.08, 0.18, 0.17, 0.14],
    "SJRb.TO": [0.01, -0.18, -0.06, -0.06, -0.06, -0.05, -0.11],
    "SU.TO": [0.03, 0.45, 0.18, 0.08, 0.04, 0.09, 0.05],
    "TCKb.TO": [-0.06, 0.29, -0.06, 0.11, 0.10, 0.23, 0.12],
    "TLM.TO": [-0.02, -0.09, -0.03, -0.10, -0.05, 0.04, 0.07],
}

# UTC epoch seconds for local (America/Toronto, EDT = UTC-4 in May 2007)
def _toronto(y, m, d, hh, mm):
    return (
        dt.datetime(y, m, d, hh, mm, tzinfo=dt.timezone(dt.timedelta(hours=-4)))
        .timestamp()
    )


def _load_days_cached(path: str, cache_root: str | None):
    """Per-symbol tick-array cache: the RDX2/XDR parse of 22 day files
    per symbol costs minutes per run, which matters because device-
    tunnel sessions die after ~10 minutes and the wf driver resumes
    itself from the chunk cache — the reload must be cheap."""
    from hhmm_tpu.apps.rdata import load_tick_days_rdata

    keys = ("price", "size", "t_seconds")
    if cache_root:
        f = os.path.join(cache_root, f"ticks_{os.path.basename(path)}.npz")
        if os.path.exists(f):
            z = np.load(f)
            return [
                {k: z[f"{k}_{i}"] for k in keys}
                for i in range(int(z["n_days"]))
            ]
    days = load_tick_days_rdata(path)
    if cache_root:
        os.makedirs(cache_root, exist_ok=True)
        np.savez(
            f,
            n_days=len(days),
            **{f"{k}_{i}": d[k] for i, d in enumerate(days) for k in keys},
        )
    return days


def _phi_draws(model, samples: np.ndarray) -> np.ndarray:
    """Posterior draws of the emission matrix, [draws, K, L]."""
    import jax
    import jax.numpy as jnp

    flat = np.asarray(samples).reshape(-1, np.asarray(samples).shape[-1])
    unpack = jax.jit(jax.vmap(lambda q: model.unpack(q)[0]["phi_k"]))
    return np.asarray(unpack(jnp.asarray(flat)))


# bear/bull pair swap, preserving up/down roles: canonical pair {0,1} =
# bear (0 down-leg, 1 up-leg), {2,3} = bull (2 up, 3 down). An
# empirical (near-)mode map, not an exact symmetry — the sparse A is
# asymmetric under it (free a01 <-> deterministic A[3,2]=1)
_PAIR_SWAP = np.array([3, 2, 1, 0])


def _relabeled_phis(model, res, price, zig):
    """Per-chain ex-post relabeling: the pair-swap symmetry (p11 <->
    1-p11 etc.) is a true posterior mode pair, and chains land in either
    mode — averaging raw draws across chains mixes the modes and shrinks
    φ̂ toward 0.5. The reference relabels its single chain by mean-return
    ordering (`tayal2009/main.R:176-184`); we apply that rule chain-wise.
    Returns ``(phis [C][draws,4,9], per_chain meta, chain_lp [C])``;
    basin selection is the caller's job (it may pool chains across
    independent restarts)."""
    from hhmm_tpu.apps.tayal.analytics import (
        map_to_topstate,
        relabel_by_return,
        topstate_runs,
    )
    from hhmm_tpu.apps.tayal.features import to_model_inputs
    from hhmm_tpu.apps.tayal.pipeline import decode_states
    import jax.numpy as jnp

    x, sign = to_model_inputs(zig.feature)
    n_ins = res.n_ins_legs
    data = {
        "x": jnp.asarray(x[:n_ins]),
        "sign": jnp.asarray(sign[:n_ins]),
        "x_oos": jnp.asarray(x[n_ins:]),
        "sign_oos": jnp.asarray(sign[n_ins:]),
    }
    chains = res.samples.shape[0]
    logp = np.asarray(res.stats["logp"])  # [chains, draws]
    chain_lp = logp.mean(axis=1)
    phis, per_chain = [], []
    for c in range(chains):
        leg_state = decode_states(model, res.samples[c], data, n_thin=40)
        top = map_to_topstate(leg_state)
        runs = topstate_runs(top, zig.start, zig.end, np.asarray(price))
        _, _, swapped = relabel_by_return(runs, top)
        phi_c = _phi_draws(model, res.samples[c])  # [draws, 4, 9]
        if swapped:
            phi_c = phi_c[:, _PAIR_SWAP, :]
        phis.append(phi_c)
        per_chain.append(
            {"swapped": bool(swapped), "phi_45": float(phi_c[:, 3, 4].mean()),
             "phi_25": float(phi_c[:, 1, 4].mean()),
             "mean_logp": float(chain_lp[c])}
        )
    return phis, per_chain, chain_lp


def _pool_dominant_basin(phis, per_chain, chain_lp, nats: float = 10.0) -> Dict:
    """Mode selection: the posterior is multimodal beyond the exact pair
    symmetry (minor modes swap emission structure within a pair); chains
    stuck in dominated modes would bias the pooled estimate, so pool
    only chains whose mean log-density reaches the best chain's (within
    a few nats — the reference's single Stan chain reports the dominant
    mode it lands in). ``phis``/``per_chain``/``chain_lp`` may span
    several independent restarts (ChEES shares adaptation within a run,
    so basin DIVERSITY comes from restarts, not from more chains)."""
    chain_lp = np.asarray(chain_lp)
    keep = chain_lp >= chain_lp.max() - nats
    phi = np.concatenate([p for p, k in zip(phis, keep) if k])
    # mode-family statistics across ALL chains: the real-data posterior
    # is rugged (chain-level φ̂₄₅ spans ~0.55-0.94 at comparable
    # density), so alongside the dominant-basin pool we report the full
    # chain-level distribution of the two published spot-check
    # coordinates — the honest context for a single-chain published
    # value (the reference's φ̂ is one Stan chain's mode)
    p45 = np.array([pc["phi_45"] for pc in per_chain])
    p25 = np.array([pc["phi_25"] for pc in per_chain])
    family = {
        "n_chains": int(len(per_chain)),
        "phi_45_mean": float(p45.mean()), "phi_45_sd": float(p45.std()),
        "phi_45_q10_q90": [float(np.quantile(p45, 0.1)), float(np.quantile(p45, 0.9))],
        "phi_25_mean": float(p25.mean()), "phi_25_sd": float(p25.std()),
        "phi_25_q10_q90": [float(np.quantile(p25, 0.1)), float(np.quantile(p25, 0.9))],
        "frac_phi45_ge_0p8": float((p45 >= 0.8).mean()),
        "lp_range_nats": [float(chain_lp.min()), float(chain_lp.max())],
    }
    return {"phi": phi, "per_chain": per_chain, "mode_family": family,
            "chains_pooled": int(keep.sum()), "chain_mean_logp": chain_lp.tolist()}


def spot_checks(phi_mean: np.ndarray) -> Dict[str, float]:
    """The write-up's φ̂₄₅/φ̂₂₅ on canonically-labeled states: φ̂₄₅ is
    the bull-pair down-leg state at symbol 5 (canonical state 3);
    φ̂₂₅ the bear-pair up-leg state (canonical state 1)."""
    return {
        "phi_45": float(phi_mean[3, 4]),
        "phi_25": float(phi_mean[1, 4]),
    }


def _sampler_config(args):
    """ChEES by default: bounded leapfrogs keep each device dispatch
    short (the tunnel kills single XLA programs that run >~10 min —
    NUTS at depth 7-8 on a ~10k-leg real window exceeds that). Gibbs
    requires the hard gate, whose strict-alternation assumption fails
    on real ticks (~1/3 same-sign adjacent legs from flat stretches) —
    keep it to synthetic model-generated data."""
    from hhmm_tpu.infer import ChEESConfig, GibbsConfig, SamplerConfig

    if args.sampler == "nuts":
        return SamplerConfig(
            num_warmup=args.warmup,
            num_samples=args.samples,
            num_chains=args.chains,
            max_treedepth=args.max_treedepth,
        )
    if args.sampler == "gibbs":
        return GibbsConfig(
            num_warmup=args.warmup,
            num_samples=args.samples,
            num_chains=args.chains,
        )
    return ChEESConfig(
        num_warmup=args.warmup,
        num_samples=args.samples,
        num_chains=max(2, args.chains),
        max_leapfrogs=args.max_leapfrogs,
    )


def _load_gto_window(window: str):
    """The G.TO study window. Two exist in the reference: `main.R:15-24`
    uses 05-01..07 / OOS 05-08; the RENDERED study (`main.Rmd:65-74`,
    main.pdf §3.6 and its Tables 3/8, "8386 zig-zags in-sample") uses
    05-04..10 / OOS 05-11. The published φ̂ spot-checks come from the
    Rmd window."""
    from hhmm_tpu.apps.rdata import load_tick_days_rdata

    all_days = load_tick_days_rdata(os.path.join(DATA_ROOT, "G.TO"))
    if window == "rmd":
        days, ins_end_t, span = all_days[3:9], (2007, 5, 10), "2007-05-04..2007-05-11"
    else:
        days, ins_end_t, span = all_days[0:6], (2007, 5, 7), "2007-05-01..2007-05-08"
    price = np.concatenate([d["price"] for d in days])
    size = np.concatenate([d["size"] for d in days])
    t = np.concatenate([d["t_seconds"] for d in days])
    ins_end = int(np.searchsorted(t, _toronto(*ins_end_t, 16, 30), "right")) - 1
    return price, size, t, ins_end, span


def run_single(args) -> Dict:
    import jax
    from hhmm_tpu.apps.tayal.pipeline import run_window

    price, size, t, ins_end, span = _load_gto_window(args.window)

    cfg = _sampler_config(args)
    from hhmm_tpu.models import TayalHHMMLite

    phis, per_chain, lps = [], [], []
    res = None
    for rs in range(max(1, args.restarts)):
        res_r = run_window(
            price, size, t, ins_end, config=cfg,
            key=jax.random.PRNGKey(args.seed + rs),
        )
        p_r, pc_r, lp_r = _relabeled_phis(TayalHHMMLite(), res_r, price, res_r.zig)
        phis += p_r
        per_chain += [{**pc, "restart": rs} for pc in pc_r]
        lps += lp_r.tolist()
        if res is None or lp_r.max() >= max(lps):
            res = res_r  # keep the restart holding the best chain
    canon = _pool_dominant_basin(phis, per_chain, lps)
    phi = canon["phi"]
    checks = spot_checks(phi.mean(axis=0))
    checks["per_chain"] = canon["per_chain"]
    checks["chains_pooled"] = canon["chains_pooled"]
    checks["chain_mean_logp"] = canon["chain_mean_logp"]
    checks["mode_family"] = canon["mode_family"]
    out = {
        "config": {
            "ticker": "G.TO",
            "window": args.window,
            "days": span,
            "n_ticks": int(len(price)),
            "n_legs": int(len(res.zig)),
            "n_ins_legs": int(res.n_ins_legs),
            "warmup": args.warmup,
            "samples": args.samples,
            "chains": args.chains,
            "restarts": max(1, args.restarts),
            "sampler": args.sampler,
            "seed": args.seed,
        },
        "published": PUBLISHED,
        "replicated": checks,
        "abs_error": {
            k: abs(checks[k] - PUBLISHED[k]) for k in PUBLISHED
        },
        "phi_mean": phi.mean(axis=0).round(4).tolist(),
        "phi_sd": phi.std(axis=0).round(4).tolist(),
        "swapped": bool(res.swapped),
        "divergence_rate": float(np.mean(res.stats.get("diverging", np.zeros(1)))),
        "summary": res.summary,
        "oos_trades_lag1": {
            "n_trades": int(len(res.trades[1].ret)),
            "total_return_pct": float(np.sum(res.trades[1].ret) * 100),
        },
        "oos_buyhold_return_pct": float(np.sum(res.bnh) * 100),
    }
    return out


def run_registered(args) -> Dict:
    """The PRE-REGISTERED round-4 protocol (`docs/phi_protocol.md`,
    committed before this ran): primary = ML-weighted pooling over
    4×8 ChEES chains (seed 9100); corroboration = soft-gate conjugate
    Gibbs, 16 chains × 6k draws with per-draw ex-post relabeling
    (seed 9200). Budgets/seeds are fixed by the protocol doc — the
    CLI sampler/budget flags are deliberately ignored here."""
    import jax
    import jax.numpy as jnp
    from hhmm_tpu.apps.tayal.features import extract_features, to_model_inputs
    from hhmm_tpu.apps.tayal.pipeline import run_window
    from hhmm_tpu.apps.tayal.replication import (
        chain_marginal_ll,
        degenerate_mode_probe,
        ml_weighted_pool,
        per_draw_relabel_stats,
    )
    from hhmm_tpu.infer import (
        ChEESConfig,
        GibbsConfig,
        SamplerConfig,
        sample_gibbs,
    )
    from hhmm_tpu.models import TayalHHMMLite

    from hhmm_tpu.batch import ResultCache, digest_key

    price, size, t, ins_end, span = _load_gto_window(args.window)
    model = TayalHHMMLite()  # gate_mode="stan"
    # per-piece result cache: the device tunnel dies ~10 min after
    # connect, so the stage must be resumable piecewise (rerun the
    # driver until it completes — the reference's RDS-cache discipline,
    # `tayal2009/main.R:91-112`)
    cache = ResultCache(args.cache_dir)

    # ---- primary arm: 4 restarts x 8 ChEES chains, ML-weighted ----
    cfg = ChEESConfig(num_warmup=400, num_samples=250, num_chains=8,
                      max_leapfrogs=args.max_leapfrogs)
    per_chain, mlls = [], []
    for rs in range(4):
        # v2: v1 computed the chain weights with make_logp (loglik +
        # bijector log-Jacobian) against the registered protocol's
        # pure-p(x|θ) definition — fixed in chain_marginal_ll and
        # re-fit under this tag (documented in docs/phi_protocol.md)
        ck = digest_key(
            {"stage": "registered-chees-v2", "window": span, "restart": rs}
        )
        hit = cache.get(ck)
        if hit is not None:
            pc_r = [
                {
                    "swapped": bool(hit["swapped"][c]),
                    "phi_45": float(hit["phi_45"][c]),
                    "phi_25": float(hit["phi_25"][c]),
                    "mean_logp": float(hit["mean_logp"][c]),
                }
                for c in range(len(hit["phi_45"]))
            ]
            mll_r = np.asarray(hit["mll"])
        else:
            res_r = run_window(
                price, size, t, ins_end, config=cfg,
                key=jax.random.PRNGKey(9100 + rs),
            )
            _, pc_r, _ = _relabeled_phis(model, res_r, price, res_r.zig)
            n_ins = res_r.n_ins_legs
            x, sign = to_model_inputs(res_r.zig.feature)
            data_ins = {
                "x": jnp.asarray(x[:n_ins]), "sign": jnp.asarray(sign[:n_ins])
            }
            mll_r = chain_marginal_ll(model, res_r.samples, data_ins)
            cache.put(
                ck,
                {
                    "swapped": np.array([pc["swapped"] for pc in pc_r]),
                    "phi_45": np.array([pc["phi_45"] for pc in pc_r]),
                    "phi_25": np.array([pc["phi_25"] for pc in pc_r]),
                    "mean_logp": np.array([pc["mean_logp"] for pc in pc_r]),
                    "mll": mll_r,
                },
            )
        per_chain += [
            {**pc, "restart": rs, "mll": float(m)} for pc, m in zip(pc_r, mll_r)
        ]
        mlls += mll_r.tolist()
        print(f"# restart {rs}: chain mll {np.round(mll_r, 1).tolist()}",
              file=sys.stderr)
    primary = ml_weighted_pool(
        {
            "phi_45": [pc["phi_45"] for pc in per_chain],
            "phi_25": [pc["phi_25"] for pc in per_chain],
        },
        np.array(mlls),
    )

    # ---- corroboration arm: soft-gate conjugate Gibbs ----
    # run as 2 cached segments of 3,000 draws (segment 1 resumes from
    # segment 0's final params — the same chain, tunnel-survivable);
    # total budget matches the registered 1,000 + 6,000 x 16
    zig = extract_features(price, size, t)
    x, sign = to_model_inputs(zig.feature)
    ins = zig.end <= ins_end
    n_ins = int(ins.sum())
    data_ins = {"x": jnp.asarray(x[:n_ins]), "sign": jnp.asarray(sign[:n_ins])}
    segs = []
    init_q = None
    for seg in range(2):
        ck = digest_key(
            {"stage": "registered-gibbs-v1", "window": span, "seg": seg}
        )
        hit = cache.get(ck)
        if hit is not None:
            qs_s, lp_s = hit["samples"], hit["logp"]
        else:
            qs_s, st_s = sample_gibbs(
                model, data_ins, jax.random.PRNGKey(9200 + seg),
                GibbsConfig(
                    num_warmup=1000 if seg == 0 else 1,
                    num_samples=3000, num_chains=16,
                ),
                init_q=init_q,
            )
            qs_s, lp_s = np.asarray(qs_s), np.asarray(st_s["logp"])
            cache.put(ck, {"samples": qs_s, "logp": lp_s})
        segs.append((np.asarray(qs_s), np.asarray(lp_s)))
        init_q = jnp.asarray(segs[-1][0][:, -1])
    qs = np.concatenate([s[0] for s in segs], axis=1)  # [16, 6000, dim]
    lp_g = np.concatenate([s[1] for s in segs], axis=1)
    kept = qs[:, ::4]  # thin x4 -> 1500/chain
    C, D, dim = kept.shape
    pd = per_draw_relabel_stats(
        model, kept.reshape(-1, dim), data_ins,
        zig.start[:n_ins], zig.end[:n_ins], price, jax.random.PRNGKey(9201),
    )
    p45 = pd["phi_45"].reshape(C, D)
    p25 = pd["phi_25"].reshape(C, D)
    gibbs = {
        "phi_45": float(p45.mean()),
        "phi_25": float(p25.mean()),
        "phi_45_sd": float(p45.std()),
        "phi_25_sd": float(p25.std()),
        "phi_45_q10_q50_q90": [float(np.quantile(p45, q)) for q in (0.1, 0.5, 0.9)],
        "frac_phi45_ge_0p8": float((p45 >= 0.8).mean()),
        "frac_swapped": float(pd["swapped"].mean()),
        "per_chain_phi_45": np.round(p45.mean(axis=1), 4).tolist(),
        "per_chain_phi_25": np.round(p25.mean(axis=1), 4).tolist(),
        "chain_mean_ll": np.round(lp_g[:, ::4].mean(axis=1), 1).tolist(),
        "kept_draws": int(C * D),
        "config": {"chains": 16, "warmup": 1000, "samples": 6000, "thin": 4,
                   "seed": 9200, "segments": 2},
    }

    # ---- investigation (mandated by decision rule step 2 when the
    # arms disagree): probe the mode each arm is reporting from ----
    probe_gibbs = degenerate_mode_probe(
        model, qs[0, -1], data_ins, jax.random.PRNGKey(77)
    )
    # short Gibbs restarted from the INTENDED-basin informed init: its
    # loglik trajectory shows whether the exact sampler leaves the
    # published basin (it does — within ~50 sweeps)
    q_informed = model.init_unconstrained(jax.random.PRNGKey(3), data_ins)
    _, st_mig = sample_gibbs(
        model, data_ins, jax.random.PRNGKey(9300),
        GibbsConfig(num_warmup=1, num_samples=300, num_chains=1),
        init_q=q_informed[None],
    )
    probe_informed = degenerate_mode_probe(
        model, q_informed, data_ins, jax.random.PRNGKey(78)
    )

    # ---- provenance: reference-mimic run (VERDICT r4 ask 7) ----
    # ONE chain at the reference's own budget and init discipline
    # (`tayal2009/main.R:34-39`: single Stan chain, 250 warmup + 250
    # iter; the informed init_unconstrained is the k-means-analog chain
    # start) — turns "this is what a single shallowly-converged chain
    # reports from the intended basin" from an inference into a
    # measurement. Round-3 upper-band chains spanned phi_45 0.85-0.94.
    ck = digest_key({"stage": "registered-provenance-v1", "window": span})
    hit = cache.get(ck)
    if hit is None:
        cfg_m = SamplerConfig(
            num_warmup=250, num_samples=250, num_chains=1, max_treedepth=10
        )
        res_m = run_window(
            price, size, t, ins_end, config=cfg_m,
            key=jax.random.PRNGKey(9400),
        )
        _, pc_m, _ = _relabeled_phis(model, res_m, price, res_m.zig)
        hit = {
            "phi_45": np.array([pc_m[0]["phi_45"]]),
            "phi_25": np.array([pc_m[0]["phi_25"]]),
            "mean_logp": np.array([pc_m[0]["mean_logp"]]),
            "divergence_rate": np.array(
                [float(np.mean(res_m.stats.get("diverging", np.zeros(1))))]
            ),
        }
        cache.put(ck, hit)
    provenance = {
        "description": (
            "reference-mimic: 1 NUTS chain, 250 warmup + 250 draws, "
            "informed (k-means-analog) init, ex-post relabel — the "
            "published number's own sampler discipline "
            "(`tayal2009/main.R:34-39`, `main.Rmd:560`)"
        ),
        "phi_45": round(float(hit["phi_45"][0]), 4),
        "phi_25": round(float(hit["phi_25"][0]), 4),
        "chain_mean_logp": round(float(hit["mean_logp"][0]), 1),
        "divergence_rate": round(float(hit["divergence_rate"][0]), 4),
        "seed": 9400,
        "expectation_preregistered": (
            "intended-basin upper band (r3 chains: 0.85-0.94) if a "
            "single budget-limited chain from the informed init stays "
            "in the basin"
        ),
        "outcome": (
            "the registered-seed chain reported the UNCONDITIONAL "
            "(degenerate-mode) values — its logp matches the "
            "degenerate mode's loglik minus the ~160-nat bijector "
            "Jacobian. The seed-sensitivity arm (all seeds recorded, "
            "run before any was inspected) spans 0.45-0.88: one of "
            "five budget-limited chains stays in the intended basin "
            "and reports 0.878 — within 0.002 of the published 0.88 "
            "— while the others wander, at chain mean logp separated "
            "by < 2.5 nats. This is the defect-#8 provenance claim as "
            "a measurement: a single 250/250 chain's spot-check is a "
            "draw from a seed lottery whose upper-band ticket "
            "reproduces the published value."
        ),
    }
    # seed-sensitivity context (extra mimic seeds, all recorded —
    # cached by scripts; absent entries are skipped, never re-run here)
    seeds_ctx = []
    for s in (9401, 9402, 9403, 9404):
        h = cache.get(
            digest_key(
                {
                    "stage": "registered-provenance-v1-seed",
                    "window": span,
                    "seed": s,
                }
            )
        )
        if h is not None:
            seeds_ctx.append(
                {
                    "seed": s,
                    "phi_45": round(float(h["phi_45"][0]), 4),
                    "phi_25": round(float(h["phi_25"][0]), 4),
                    "chain_mean_logp": round(float(h["mean_logp"][0]), 1),
                }
            )
    if seeds_ctx:
        provenance["seed_sensitivity"] = seeds_ctx

    # ---- fixed decision rule (`docs/phi_protocol.md`) ----
    agree = {
        k: abs(primary[k] - gibbs[k]) for k in ("phi_45", "phi_25")
    }
    corroborated = all(v <= 0.05 for v in agree.values())
    abs_err = {k: abs(primary[k] - PUBLISHED[k]) for k in PUBLISHED}
    point_match = all(v <= 0.05 for v in abs_err.values())
    return {
        "protocol": "docs/phi_protocol.md (pre-registered round 4)",
        "window": span,
        "published": PUBLISHED,
        "headline": {
            "estimator": "ml_weighted_32chain_chees",
            "scope": (
                "conditional on the intended (sign-consistent) basin — "
                "the published number's provenance; the model's exact "
                "unconditional posterior concentrates on the "
                "emission-only degenerate mode (reference defect #8, "
                "see investigation + docs/tayal2009.md)"
            ),
            "phi_45": round(primary["phi_45"], 4),
            "phi_25": round(primary["phi_25"], 4),
            "eff_chains": round(primary["eff_chains"], 2),
            "top_chain_share": round(primary["top_chain_share"], 4),
            "abs_error": {k: round(v, 4) for k, v in abs_err.items()},
            "point_match_le_0p05": point_match,
        },
        "gibbs_crosscheck": gibbs,
        "provenance": provenance,
        "corroboration": {
            "abs_gap_primary_vs_gibbs": {k: round(v, 4) for k, v in agree.items()},
            "corroborated_le_0p05": corroborated,
            "note": (
                "the two arms answer different questions when they "
                "disagree at this scale: Gibbs integrates the exact "
                "soft-gate posterior (dominated by the degenerate "
                "emission-only mode), HMC stays in the intended basin "
                "it was initialized in — exactly how the reference's "
                "single Stan chain produced the published value"
            ),
        },
        "investigation": {
            "finding": (
                "reference defect #8: the soft sign gate "
                "(`hhmm-tayal2009.stan:57-66`) charges NO transition "
                "factor on sign-inconsistent destinations (structural "
                "zeros of A included), opening an emission-only path "
                "track; the exact posterior concentrates there and the "
                "published spot-checks are conditional on the intended "
                "basin"
            ),
            "gibbs_mode_probe": probe_gibbs,
            "informed_init_probe": probe_informed,
            "gibbs_from_informed_init_loglik_every_50": np.round(
                np.asarray(st_mig["logp"])[0, ::50], 1
            ).tolist(),
        },
        "primary_per_chain": per_chain,
        "primary_weights": primary["weights"],
    }


def run_wf(args) -> Dict:
    import jax
    from hhmm_tpu.apps.tayal.wf import build_tasks, wf_trade

    symbols = sorted(
        d for d in os.listdir(DATA_ROOT)
        if os.path.isdir(os.path.join(DATA_ROOT, d))
    )
    if args.symbols:
        symbols = [s for s in symbols if s in args.symbols.split(",")]
    days = {
        sym: _load_days_cached(os.path.join(DATA_ROOT, sym), args.cache_dir)
        for sym in symbols
    }
    tasks = build_tasks(days, train_days=5, trade_days=1)
    if args.max_tasks:
        tasks = tasks[: args.max_tasks]
    cfg = _sampler_config(args)
    # the replication protocol is chees/nuts + stan gate + the
    # reference's xts tick expansion
    gate_mode, expansion = "stan", "xts"
    import time as _time

    phases: Dict[str, float] = {}
    t_wf = _time.time()
    results = wf_trade(
        tasks,
        config=cfg,
        key=jax.random.PRNGKey(args.seed),
        chunk_size=args.chunk,
        cache_dir=args.cache_dir,
        gate_mode=gate_mode,
        expansion=expansion,
        warm_start=args.warm_start,
        phase_timings=phases,
    )
    wf_seconds = round(_time.time() - t_wf, 1)

    # per-strategy daily-return table (`main.Rmd:800`: one compound
    # daily return per (task, strategy); strategies = buy&hold + lags)
    lags = sorted(results[0].trades)
    table: List[Dict] = []
    for r in results:
        row = {
            "symbol": r.symbol,
            "window": r.window,
            "bnh_pct": float((np.prod(1 + r.bnh) - 1) * 100),
            "diverged": r.diverged,
            "n_oos_legs": r.n_oos_legs,
            "oos_leg_switches": r.oos_leg_switches,
            "chains_pooled": r.chains_pooled,
            "run_len_mean_ticks": round(r.run_len_mean, 2),
            "run_len_median_ticks": r.run_len_median,
        }
        for lag in lags:
            row[f"lag{lag}_pct"] = float((np.prod(1 + r.trades[lag].ret) - 1) * 100)
            row[f"lag{lag}_sum_pct"] = float(np.sum(r.trades[lag].ret) * 100)
            row[f"lag{lag}_trades"] = int(len(r.trades[lag].ret))
        table.append(row)

    def _col(name, rows=None):
        return np.array([row[name] for row in (rows if rows is not None else table)])

    names = ["bnh"] + [f"lag{lag}" for lag in lags]

    def _cols(rows=None):
        return {
            n: _col(("bnh_pct" if n == "bnh" else f"{n}_pct"), rows) for n in names
        }

    strategies = _cols()
    agg = {
        name: {
            "mean_daily_pct": float(v.mean()),
            "median_daily_pct": float(np.median(v)),
            "sd_daily_pct": float(v.std(ddof=1)),
            "min_daily_pct": float(v.min()),
            "max_daily_pct": float(v.max()),
            "iqr_daily_pct": float(np.subtract(*np.percentile(v, [75, 25]))),
            "total_compound_pct": float((np.prod(1 + v / 100) - 1) * 100),
            "hit_rate": float((v > 0).mean()),
            "n": int(v.size),
        }
        for name, v in strategies.items()
    }

    # --- comparison vs the published tables (main.pdf) ---
    statkey = {
        "mean": "mean_daily_pct",
        "median": "median_daily_pct",
        "sd": "sd_daily_pct",
        "min": "min_daily_pct",
        "max": "max_daily_pct",
        "iqr": "iqr_daily_pct",
    }
    agg_vs_published = {
        stat: {
            "published": PUBLISHED_T6[stat],
            "replicated": [round(agg[n][statkey[stat]], 2) for n in names],
        }
        for stat in PUBLISHED_T6
    }
    stock_totals = {}
    for sym in symbols:
        rows = [row for row in table if row["symbol"] == sym]
        cols = _cols(rows)
        repl = [
            round(float(np.prod(1 + cols[n] / 100) - 1), 2) for n in names
        ]
        entry = {"replicated_total": repl, "n_windows": len(rows)}
        if sym in PUBLISHED_STOCK_TOTALS:
            entry["published_total"] = PUBLISHED_STOCK_TOTALS[sym]
        stock_totals[sym] = entry
    gto = {}
    gto_rows = sorted(
        (row for row in table if row["symbol"] == "G.TO"), key=lambda r: r["window"]
    )
    # pair windows with published days positionally — only safe when the
    # full calendar ran (window w trades PUBLISHED_T5_DAYS[w]); a
    # partial run (--max-tasks/--symbols) would silently mislabel rows
    if len(gto_rows) == len(PUBLISHED_T5_DAYS) and [
        r["window"] for r in gto_rows
    ] == list(range(len(PUBLISHED_T5_DAYS))):
        for day, row in zip(PUBLISHED_T5_DAYS, gto_rows):
            gto[day] = {
                "published": PUBLISHED_T5[day],
                "replicated": [round(row["bnh_pct"], 2)]
                + [round(row[f"lag{lag}_pct"], 2) for lag in lags],
            }
    else:
        gto["skipped"] = (
            f"partial run ({len(gto_rows)} G.TO windows, need "
            f"{len(PUBLISHED_T5_DAYS)} for day alignment)"
        )

    return {
        "config": {
            "symbols": symbols,
            "n_tasks": len(tasks),
            "n_returns": len(tasks) * (len(lags) + 1),
            "warmup": args.warmup,
            "samples": args.samples,
            "chains": args.chains,
            "sampler": args.sampler,
            "gate_mode": gate_mode,
            "expansion": expansion,
            "chunk": args.chunk,
            "seed": args.seed,
            "warm_start": args.warm_start,
        },
        "wall_clock": {
            "seconds": wf_seconds,
            "phases": phases,
            "note": "end-to-end wf_trade call; phases from its "
            "phase_timings surface. A resumed run (digest-cache hits) "
            "times only the resumed work — single-shot runs are the "
            "comparable ones",
        },
        "reference_volume": "12 stocks x ~17 windows x 7 strategies = 1428 returns (`tayal2009/main.Rmd:800`)",
        "aggregate": agg,
        "aggregate_vs_published_t6": agg_vs_published,
        "stock_totals_vs_published": stock_totals,
        "gto_daily_vs_published_t5": gto,
        "per_window": table,
    }


def run_gto_band(args) -> Dict:
    """Seed-ensemble error bars for the per-day G.TO backtest rows
    (VERDICT r3 #8): re-run the WORST-deviating windows vs the
    published Table 5 with 5 independent fit+decode seeds and record
    the per-(day, lag) spread. A published row inside the band is
    explained by seed-level basin/decode variance; a row outside it is
    a real deviation."""
    import jax
    from hhmm_tpu.apps.tayal.wf import build_tasks, wf_trade

    # pick the worst days from the committed wf record
    path = os.path.join(RESULTS, "tayal_replication.json")
    with open(path) as f:
        rec = json.load(f)
    gto = rec["wf"]["gto_daily_vs_published_t5"]
    devs = {
        day: float(np.abs(np.array(v["replicated"]) - np.array(v["published"])).max())
        for day, v in gto.items()
        if isinstance(v, dict)
    }
    worst_days = sorted(devs, key=devs.get, reverse=True)[: args.band_days]
    win_of_day = {d: i for i, d in enumerate(PUBLISHED_T5_DAYS)}
    windows = sorted(win_of_day[d] for d in worst_days)

    days = {
        "G.TO": _load_days_cached(os.path.join(DATA_ROOT, "G.TO"), args.cache_dir)
    }
    tasks = [
        t for t in build_tasks(days, train_days=5, trade_days=1)
        if t.window in windows
    ]
    cfg = _sampler_config(args)
    lags = (0, 1, 2, 3, 4, 5)
    ens: Dict[str, Dict[str, List[float]]] = {
        d: {f"lag{l}": [] for l in lags} for d in worst_days
    }
    for s in range(args.band_seeds):
        results = wf_trade(
            tasks,
            config=cfg,
            key=jax.random.PRNGKey(9400 + s),
            chunk_size=args.chunk,
            cache_dir=None,  # fresh fits per seed — the point is variance
            gate_mode="stan",
            expansion="xts",
        )
        for r in results:
            day = PUBLISHED_T5_DAYS[r.window]
            for lag in lags:
                ens[day][f"lag{lag}"].append(
                    float((np.prod(1 + r.trades[lag].ret) - 1) * 100)
                )
        print(f"# band seed {s} done", file=sys.stderr)

    out_days = {}
    for d in worst_days:
        row = {"published": PUBLISHED_T5[d], "window": win_of_day[d]}
        for lag in lags:
            v = np.array(ens[d][f"lag{lag}"])
            pub = PUBLISHED_T5[d][1 + lag]
            row[f"lag{lag}"] = {
                "seeds_pct": np.round(v, 2).tolist(),
                "mean": round(float(v.mean()), 2),
                "sd": round(float(v.std(ddof=1)), 2),
                "band_min_max": [round(float(v.min()), 2), round(float(v.max()), 2)],
                "published_pct": pub,
                "published_in_band": bool(v.min() - 1e-9 <= pub <= v.max() + 1e-9),
            }
        out_days[d] = row
    n_cells = sum(
        1 for d in out_days for l in lags
    )
    n_in = sum(
        1 for d in out_days for l in lags if out_days[d][f"lag{l}"]["published_in_band"]
    )
    return {
        "note": (
            "5-seed fit+decode ensemble on the worst-deviating G.TO "
            "windows; published Table 5 value inside the seed band => "
            "deviation explained by basin/decode variance"
        ),
        "seeds": args.band_seeds,
        "days": out_days,
        "published_in_band_frac": round(n_in / max(1, n_cells), 3),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("stage", choices=["single", "wf", "registered", "gto-band"])
    ap.add_argument("--band-days", type=int, default=3,
                    help="gto-band: how many worst-deviating days")
    ap.add_argument("--band-seeds", type=int, default=5)
    ap.add_argument("--warmup", type=int, default=250)
    ap.add_argument("--samples", type=int, default=250)
    ap.add_argument("--chains", type=int, default=4)
    ap.add_argument("--max-treedepth", type=int, default=8)
    ap.add_argument("--max-leapfrogs", type=int, default=32)
    ap.add_argument("--sampler", choices=["chees", "nuts", "gibbs"], default="chees")
    ap.add_argument("--seed", type=int, default=9000)
    ap.add_argument("--chunk", type=int, default=64)
    ap.add_argument("--symbols", type=str, default="")
    ap.add_argument("--window", choices=["rmd", "mainr"], default="rmd")
    ap.add_argument(
        "--restarts",
        type=int,
        default=1,
        help="single stage: independent fit restarts (fresh adaptation "
        "per restart) pooled by dominant basin across ALL chains — "
        "ChEES shares step-size/trajectory adaptation within a run, so "
        "basin diversity comes from restarts, not from more chains",
    )
    ap.add_argument("--max-tasks", type=int, default=0)
    ap.add_argument("--cache-dir", type=str, default=None)
    ap.add_argument(
        "--warm-start",
        action="store_true",
        help="wf stage: pilot-seed every window's chains from its "
        "symbol's first-window fit (the idiomatic warm start the "
        "reference cannot do, `hassan2005/main.Rmd:795`)",
    )
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()
    if args.sampler == "gibbs" and args.stage == "single":
        raise SystemExit(
            "the single stage's run_window drives density-based HMC; "
            "for conjugate Gibbs on the real window use the "
            "'registered' stage (soft-gate Gibbs is exact as of round "
            "4 — see docs/phi_protocol.md). The wf stage accepts "
            "--sampler gibbs (fit_batched dispatches it)."
        )

    if args.cache_dir:
        # persistent XLA compilation cache: tunnel sessions die ~10 min
        # after connect, so resumed runs must not re-pay multi-minute
        # compiles on every relaunch
        import jax

        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(args.cache_dir, "xla_cache")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

    runner = {
        "single": run_single,
        "wf": run_wf,
        "registered": run_registered,
        "gto-band": run_gto_band,
    }
    out = runner[args.stage](args)
    os.makedirs(RESULTS, exist_ok=True)
    path = args.out or os.path.join(RESULTS, "tayal_replication.json")
    merged = {}
    if os.path.exists(path):
        with open(path) as f:
            merged = json.load(f)
    # the warm-started wf is recorded BESIDE the cold protocol run,
    # never over it (the replication record is cold-start); likewise the
    # conjugate-Gibbs arm of the backtest records beside the ChEES
    # protocol arm, never over it
    record_key = (
        "wf_warm" if (args.stage == "wf" and args.warm_start) else args.stage
    )
    if args.stage == "wf" and args.sampler == "gibbs":
        record_key = "wf_gibbs_warm" if args.warm_start else "wf_gibbs"
    merged[record_key] = out
    with open(path, "w") as f:
        json.dump(merged, f, indent=1)
    summary = out.get(
        "headline",
        out.get(
            "replicated",
            out.get(
                "aggregate", {"published_in_band_frac": out.get("published_in_band_frac")}
            ),
        ),
    )
    print(json.dumps({args.stage: summary}, indent=1))
    print("wrote", os.path.abspath(path))


if __name__ == "__main__":
    main()
