"""Figures from the committed real-data replication artifacts.

Reads ``results/tayal_replication.json`` (no TPU needed) and renders:

- ``tayal_phi_posterior.png`` — the G.TO 4x9 emission posterior
  (mean ± sd per state) with the published spot-checks marked, the
  equivalent of the reference's per-state parameter panels
  (`tayal2009/main.Rmd:540-558`);
- ``tayal_wf_lags.png`` — mean daily return and hit rate per strategy
  (buy-and-hold + lags 0..5) over the 204-window backtest, the summary
  view of the reference's 1,428-return appendix table
  (`tayal2009/Rmd/appendix-wf.Rmd`);
- ``docs/appendix-wf.md`` + ``appendix_equity_<SYM>.png`` — the
  per-stock appendix layer (`tayal2009/Rmd/appendix-wf.Rmd`, main.pdf
  §5.2): per-day compound-return tables and equity lines for all 12
  tickers, with the published per-stock Total row for comparison.

Run: ``python examples/replication_figures.py`` (writes docs/figures).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# sibling driver (for the published-table constants), importable even
# when this module is imported from outside examples/ (tests)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(ROOT, "results", "tayal_replication.json")
OUT = os.path.join(ROOT, "docs", "figures")


def main():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    with open(RESULTS) as f:
        rep = json.load(f)
    os.makedirs(OUT, exist_ok=True)

    # --- emission posterior panels ---
    single = rep["single"]
    mean = np.asarray(single["phi_mean"])  # [4, 9]
    sd = np.asarray(single["phi_sd"])
    titles = [
        "state 1 (bear, down legs)",
        "state 2 (bear, up legs)",
        "state 3 (bull, up legs)",
        "state 4 (bull, down legs)",
    ]
    fig, axes = plt.subplots(1, 4, figsize=(13, 3.2), sharey=True)
    for k, ax in enumerate(axes):
        ax.bar(np.arange(1, 10), mean[k], yerr=sd[k], color="#4878b0", capsize=2)
        ax.set_title(titles[k], fontsize=9)
        ax.set_xlabel("symbol")
        ax.set_xticks(range(1, 10))
    axes[0].set_ylabel("posterior probability")
    axes[0].set_ylim(0, 1.0)
    # published spot checks (main.Rmd:560): phi_45 on panel 4, phi_25 on 2
    axes[3].axhline(0.88, ls="--", color="#b04848", lw=1)
    axes[3].annotate("published 0.88", (0.6, 0.92), fontsize=8, color="#b04848")
    axes[1].axhline(0.80, ls="--", color="#b04848", lw=1)
    axes[1].annotate("published 0.80", (0.6, 0.84), fontsize=8, color="#b04848")
    fam = single["replicated"].get("mode_family")
    fam_note = (
        f"; chain-level mode family phi_45 = {fam['phi_45_mean']:.2f} ± "
        f"{fam['phi_45_sd']:.2f} (q90 {fam['phi_45_q10_q90'][1]:.2f}) — "
        "the published value is ONE Stan chain from this family"
        if fam
        else ""
    )
    fig.suptitle(
        "G.TO emission posterior (real TSX ticks, Rmd window) — "
        f"dominant-basin pool phi_45 = {single['replicated']['phi_45']:.3f}, "
        f"phi_25 = {single['replicated']['phi_25']:.3f}{fam_note}",
        fontsize=9,
    )
    fig.tight_layout()
    path = os.path.join(OUT, "tayal_phi_posterior.png")
    fig.savefig(path, dpi=110, bbox_inches="tight")
    print("wrote", path)

    # --- walk-forward strategy summary ---
    agg = rep["wf"]["aggregate"]
    names = ["bnh"] + [f"lag{i}" for i in range(6)]
    means = [agg[n]["mean_daily_pct"] for n in names]
    hits = [agg[n]["hit_rate"] for n in names]
    fig, ax1 = plt.subplots(figsize=(7, 3.6))
    xs = np.arange(len(names))
    ax1.bar(xs, means, color=["#777777"] + ["#4878b0"] * 6)
    ax1.set_xticks(xs)
    ax1.set_xticklabels(["buy&hold"] + [f"lag {i}" for i in range(6)])
    ax1.set_ylabel("mean daily return (%)")
    ax1.axhline(0, color="black", lw=0.8)
    ax2 = ax1.twinx()
    ax2.plot(xs, hits, "o-", color="#b04848", ms=4)
    ax2.set_ylabel("hit rate", color="#b04848")
    ax2.set_ylim(0, 1)
    n = rep["wf"]["config"]["n_tasks"]
    ax1.set_title(
        f"Walk-forward backtest, 12 TSX tickers x {n // 12} windows "
        f"({n} trading days; signal at a zig-zag extremum, so lag 0 fills "
        "at the locally worst price)",
        fontsize=9,
    )
    fig.tight_layout()
    path = os.path.join(OUT, "tayal_wf_lags.png")
    fig.savefig(path, dpi=110, bbox_inches="tight")
    print("wrote", path)

    appendix(rep, plt)


def appendix(rep, plt):
    """Per-stock appendix (`tayal2009/Rmd/appendix-wf.Rmd`, main.pdf
    §5.2): one per-day return table + one equity-line figure per
    ticker, generated from the committed ``wf.per_window`` artifact."""
    from tayal_replication import PUBLISHED_T5_DAYS

    wf = rep["wf"]
    rows = wf["per_window"]
    lags = sorted(
        int(k[3:-4]) for k in rows[0] if k.startswith("lag") and k.endswith("_pct")
        and "_sum" not in k and "_trades" not in k
    )
    names = ["bnh"] + [f"lag{lag}" for lag in lags]
    labels = ["buy&hold"] + [f"lag {lag}" for lag in lags]
    stock_pub = wf.get("stock_totals_vs_published", {})
    symbols = sorted({r["symbol"] for r in rows})

    md = [
        "# Appendix — per-stock walk-forward results",
        "",
        "Analog of the reference's `tayal2009/Rmd/appendix-wf.Rmd` "
        "(rendered as main.pdf §5.2): per-day compound returns (%) of "
        "buy-and-hold and the lag-0..5 top-state strategies, one table "
        "and equity line per ticker, from the committed "
        "`results/tayal_replication.json` `wf.per_window` record "
        f"({len(rows)} windows, {wf['config']['n_returns']} returns). "
        "`Total` compounds the daily returns; `Published total` is the "
        "reference's per-stock Total row (main.pdf Tables 9-20, as "
        "fractions). Generated by `examples/replication_figures.py`.",
        "",
    ]
    for sym in symbols:
        srows = sorted((r for r in rows if r["symbol"] == sym), key=lambda r: r["window"])
        full_cal = len(srows) == len(PUBLISHED_T5_DAYS)
        md += [f"## {sym}", ""]
        md.append("| day | " + " | ".join(labels) + " |")
        md.append("|---|" + "---|" * len(labels))
        series = {n: [] for n in names}
        for r in srows:
            day = PUBLISHED_T5_DAYS[r["window"]] if full_cal else f"w{r['window']}"
            vals = [r["bnh_pct"]] + [r[f"lag{lag}_pct"] for lag in lags]
            for n, v in zip(names, vals):
                series[n].append(v)
            md.append(
                f"| {day} | " + " | ".join(f"{v:.2f}" for v in vals) + " |"
            )
        totals = [
            float(np.prod(1 + np.array(series[n]) / 100) - 1) for n in names
        ]
        md.append(
            "| **Total %** | "
            + " | ".join(f"{v * 100:.1f}" for v in totals) + " |"
        )
        pub = stock_pub.get(sym, {}).get("published_total")
        if pub:  # published rows are fractions — render in % too
            md.append(
                "| **Published total %** | "
                + " | ".join(f"{v * 100:.1f}" for v in pub) + " |"
            )
        md += ["", f"![{sym} equity](figures/appendix_equity_{sym}.png)", ""]

        fig, ax = plt.subplots(figsize=(7, 3.2))
        xs = np.arange(len(srows) + 1)
        for n, lab in zip(names, labels):
            eq = np.concatenate([[1.0], np.cumprod(1 + np.array(series[n]) / 100)])
            kw = {"color": "#777777", "lw": 2} if n == "bnh" else {"lw": 1}
            ax.plot(xs, eq, label=lab, **kw)
        ax.set_title(f"{sym} — walk-forward equity (per-day compounding)", fontsize=9)
        ax.set_xlabel("trading day")
        ax.set_ylabel("equity (x initial)")
        ax.axhline(1.0, color="black", lw=0.6)
        ax.legend(fontsize=7, ncol=4)
        fig.tight_layout()
        path = os.path.join(OUT, f"appendix_equity_{sym}.png")
        fig.savefig(path, dpi=110, bbox_inches="tight")
        plt.close(fig)
        print("wrote", path)

    apx = os.path.join(ROOT, "docs", "appendix-wf.md")
    with open(apx, "w") as f:
        f.write("\n".join(md))
    print("wrote", apx)


if __name__ == "__main__":
    main()
