"""IOHMM driver — the reference's `iohmm-reg/main.R` and
`iohmm-mix/main.R`: simulate an input-driven HMM, fit, summarize,
relabel, and report state recovery.

  python examples/iohmm_main.py                 # regression emissions
  python examples/iohmm_main.py --variant hmix  # hierarchical mixture
"""

from __future__ import annotations

import sys, os
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import configure, print_summary, run_sampler, save_figure, standard_parser


def main() -> None:
    ap = standard_parser(__doc__)
    ap.add_argument("--variant", choices=("reg", "hmix"), default="reg")
    ap.add_argument("--T", type=int, default=300)
    args = ap.parse_args()
    cfg = configure(args)

    import jax
    import jax.numpy as jnp

    from hhmm_tpu.infer import confusion_matrix, greedy_relabel
    from hhmm_tpu.models import IOHMMHMix, IOHMMReg
    from hhmm_tpu.sim import iohmm_sim, obsmodel_mix, obsmodel_reg

    rng = np.random.default_rng(args.seed)
    if args.variant == "reg":
        # `iohmm-reg/main.R:10-22`: T=300, K=3, M=4
        K, M = 3, 4
        u = np.column_stack([np.ones(args.T), rng.normal(size=(args.T, M - 1))])
        w = rng.normal(size=(K, M)) * 1.5
        b = rng.normal(size=(K, M)) * 2.0
        sim = iohmm_sim(jax.random.PRNGKey(args.seed), u, w, obsmodel_reg(b, np.full(K, 0.4)))
        model = IOHMMReg(K=K, M=M)
    else:
        # `iohmm-mix/main.R:10-39`: K=4, L=3 hierarchical mixture
        from hhmm_tpu.apps.hassan.wf import DEFAULT_HYPERPARAMS

        K, M, L = 4, 4, 3
        u = np.column_stack([np.ones(args.T), rng.normal(size=(args.T, M - 1))])
        w = rng.normal(size=(K, M)) * 1.5
        lambdas = rng.dirichlet(np.ones(L), size=K)
        mu = np.sort(rng.normal(size=(K, L)) * 3.0, axis=1) + np.arange(K)[:, None] * 4.0
        sim = iohmm_sim(
            jax.random.PRNGKey(args.seed), u, w, obsmodel_mix(lambdas, mu, np.full((K, L), 0.5))
        )
        model = IOHMMHMix(K=K, M=M, L=L, hyperparams=DEFAULT_HYPERPARAMS)

    data = {"u": jnp.asarray(sim["u"]), "x": jnp.asarray(sim["x"])}
    from hhmm_tpu.infer import init_chains

    theta0 = init_chains(model, jax.random.PRNGKey(args.seed + 1), data, cfg.num_chains)
    qs, stats = run_sampler(
        None, jax.random.PRNGKey(args.seed + 2), theta0, cfg, vg_fn=model.make_vg(data)
    )
    print(f"divergence rate: {float(np.asarray(stats['diverging']).mean()):.4f}")
    print_summary(model.constrained_draws(qs))

    # greedy relabeling + confusion vs simulated states (`iohmm-reg/main.R:78-94`)
    gen = model.generated(qs[:, :: max(1, cfg.num_samples // 50)], data)
    alpha = np.asarray(gen["alpha"]).mean(axis=(0, 1))
    z_true = np.asarray(sim["z"])
    z_hat = alpha.argmax(axis=1)
    perm = greedy_relabel(z_true, z_hat, model.K)
    z_hat = perm[z_hat]
    print("filtered-state confusion (rows=true):")
    print(confusion_matrix(z_true, z_hat, model.K))
    print(f"filtered accuracy: {(z_hat == z_true).mean():.3f}")

    if args.plots_dir:
        import matplotlib

        matplotlib.use("Agg")
        from hhmm_tpu.viz.plots import plot_inputoutput

        fig = plot_inputoutput(np.asarray(sim["x"]), np.asarray(sim["u"]), z=z_true)
        save_figure(fig, args.plots_dir, f"iohmm_{args.variant}_inputoutput.png")


if __name__ == "__main__":
    main()
