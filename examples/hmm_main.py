"""HMM driver — the reference's `hmm/main.R`, `main-multinom.R`, and
`main-multinom-semisup.R` in one script: simulate → fit → posterior
summary → state-recovery confusion tables → plots.

  python examples/hmm_main.py                      # Gaussian K=2, T=500
  python examples/hmm_main.py --variant multinom   # K=3, L=5
  python examples/hmm_main.py --variant semisup    # K=4, L=9 Tayal-shaped
"""

from __future__ import annotations

import sys, os
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import configure, print_summary, run_sampler, save_figure, standard_parser


def main() -> None:
    ap = standard_parser(__doc__)
    ap.add_argument("--variant", choices=("gaussian", "multinom", "semisup"), default="gaussian")
    ap.add_argument("--T", type=int, default=500)
    args = ap.parse_args()
    cfg = configure(args)

    import jax
    import jax.numpy as jnp

    from hhmm_tpu.infer import confusion_matrix, greedy_relabel
    from hhmm_tpu.models import GaussianHMM, MultinomialHMM, SemisupMultinomialHMM
    from hhmm_tpu.sim import hmm_sim, obsmodel_categorical, obsmodel_gaussian

    key = jax.random.PRNGKey(args.seed)

    if args.variant == "gaussian":
        # `hmm/main.R:7-11` shapes: sticky 2-state chain, separated means
        K = 2
        A = np.array([[0.9, 0.1], [0.2, 0.8]])
        p1 = np.array([0.5, 0.5])
        z, x = hmm_sim(key, args.T, A, p1, obsmodel_gaussian(np.array([-1.0, 2.5]), np.array([0.6, 1.0])))
        model = GaussianHMM(K=K)
        data = {"x": jnp.asarray(x)}
    elif args.variant == "multinom":
        # `hmm/main-multinom.R:7-27`: K=3, L=5
        K, L = 3, 5
        rng = np.random.default_rng(args.seed)
        A = np.array([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.15, 0.15, 0.7]])
        p1 = np.ones(K) / K
        phi = rng.dirichlet(np.ones(L) * 0.8, size=K)
        z, x = hmm_sim(key, args.T, A, p1, obsmodel_categorical(phi))
        model = MultinomialHMM(K=K, L=L)
        data = {"x": jnp.asarray(np.asarray(x, np.int32))}
    else:
        # `hmm/main-multinom-semisup.R:7-41`: K=4, L=9, Tayal-shaped sparse A
        K, L = 4, 9
        rng = np.random.default_rng(args.seed)
        A = np.array(
            [[0.0, 0.4, 0.6, 0.0], [1.0, 0.0, 0.0, 0.0], [0.3, 0.0, 0.0, 0.7], [0.0, 0.0, 1.0, 0.0]]
        )
        p1 = np.array([0.5, 0.0, 0.5, 0.0])
        phi = rng.dirichlet(np.ones(L) * 1.5, size=K)
        z, x = hmm_sim(key, args.T, A, p1, obsmodel_categorical(phi))
        groups = np.array([0, 1, 1, 0])
        g = groups[np.asarray(z)]
        model = SemisupMultinomialHMM(K=K, L=L, groups=groups, gate_mode="hard")
        data = {"x": jnp.asarray(np.asarray(x, np.int32)), "g": jnp.asarray(g)}

    from hhmm_tpu.infer import init_chains

    theta0 = init_chains(model, jax.random.PRNGKey(args.seed + 1), data, cfg.num_chains)
    qs, stats = run_sampler(
        None, jax.random.PRNGKey(args.seed + 2), theta0, cfg, vg_fn=model.make_vg(data)
    )
    print(f"divergence rate: {float(np.asarray(stats['diverging']).mean()):.4f}")
    print_summary(model.constrained_draws(qs))

    # state recovery (`hmm/main.R:89-101`): hard-classified filtered
    # states and Viterbi vs simulated truth, after greedy relabeling
    gen = model.generated(qs[:, :: max(1, cfg.num_samples // 50)], data)
    alpha = np.asarray(gen["alpha"]).mean(axis=(0, 1))
    z_hat = alpha.argmax(axis=1)
    z_true = np.asarray(z)
    perm = greedy_relabel(z_true, z_hat, model.K)
    z_hat = perm[z_hat]
    print("filtered-state confusion (rows=true):")
    print(confusion_matrix(z_true, z_hat, model.K))
    print(f"filtered accuracy: {(z_hat == z_true).mean():.3f}")

    if args.plots_dir:
        import matplotlib

        matplotlib.use("Agg")
        from hhmm_tpu.viz.plots import plot_statepath, plot_stateprobability

        fig = plot_stateprobability(
            np.asarray(gen["alpha"]).reshape(-1, *gen["alpha"].shape[2:]),
            np.asarray(gen["gamma"]).reshape(-1, *gen["gamma"].shape[2:]),
            z=z_true,
        )
        save_figure(fig, args.plots_dir, f"hmm_{args.variant}_stateprob.png")
        fig = plot_statepath(np.asarray(gen["zstar"]).reshape(-1, gen["zstar"].shape[-1]), z=z_true)
        save_figure(fig, args.plots_dir, f"hmm_{args.variant}_statepath.png")


if __name__ == "__main__":
    main()
