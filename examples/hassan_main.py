"""Hassan (2005) driver — the reference's `hassan2005/main.R`: build the
OHLC dataset, run the warm-started walk-forward forecast, and report the
out-of-sample error table (MSE / MAPE / R²).

  python examples/hassan_main.py                       # simulated OHLC
  python examples/hassan_main.py --csv prices.csv      # your own data
"""

from __future__ import annotations

import sys, os
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import configure, save_figure, standard_parser


def main() -> None:
    ap = standard_parser(__doc__)
    ap.add_argument("--csv", default=None, help="OHLC CSV (open/high/low/close columns)")
    ap.add_argument("--T", type=int, default=160, help="simulated days when no --csv")
    ap.add_argument("--train-frac", type=float, default=0.75)
    ap.add_argument("--K", type=int, default=4)
    ap.add_argument("--L", type=int, default=3)
    args = ap.parse_args()
    cfg = configure(args)

    import jax

    from hhmm_tpu.apps.data_io import load_ohlc_csv
    from hhmm_tpu.apps.hassan.data import simulate_ohlc
    from hhmm_tpu.apps.hassan.wf import wf_forecast

    if args.csv:
        ohlc = load_ohlc_csv(args.csv)
    else:
        ohlc = simulate_ohlc(np.random.default_rng(args.seed), args.T)
    train_len = int(len(ohlc) * args.train_frac)
    print(f"{len(ohlc)} days, training on first {train_len}, "
          f"{len(ohlc) - train_len} walk-forward steps")

    res = wf_forecast(
        ohlc,
        train_len=train_len,
        K=args.K,
        L=args.L,
        config=cfg,
        key=jax.random.PRNGKey(args.seed),
    )
    print(f"mean divergence rate: {float(res.diverged.mean()):.4f}")
    print("out-of-sample errors (the `hassan2005/main.Rmd:920-933` table):")
    for k, v in res.errors.items():
        print(f"  {k:>5}: {v:.5g}")

    if args.plots_dir:
        import matplotlib

        matplotlib.use("Agg")
        from hhmm_tpu.viz.plots import plot_seqforecast

        bands = np.quantile(res.forecasts, [0.1, 0.5, 0.9], axis=1)  # [3, S]
        fig = plot_seqforecast(np.asarray(ohlc[:train_len, 3]), bands)
        save_figure(fig, args.plots_dir, "hassan_forecast.png")


if __name__ == "__main__":
    main()
