"""Hassan (2005) accuracy record on a frozen synthetic benchmark.

Exact replication of the reference's OOS tables (`hassan2005/main.Rmd:
920-933,1024-1037`: LUV MSE 0.0792 / MAPE 1.57% / R² 0.8689; RYA.L
1743.143 / 1.30% / 0.9409) is impossible in this environment — the
reference fetched live Yahoo/Google quotes (network) and did not commit
the OHLC data. What CAN be recorded and regressed is the same pipeline
on documented, frozen-seed synthetic OHLC: two regime-switching price
paths ("SYN-A" low-vol trending, "SYN-B" high-vol mean-reverting), the
reference's K=4/L=3 model config, and the same error metrics. The
numbers land in ``results/hassan_replication.json`` and are quoted in
``docs/hassan2005.md``.

Run from the repo root: ``python examples/hassan_replication.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULTS = os.path.join(os.path.dirname(__file__), "..", "results")

# frozen benchmark definitions: (seed, T, vol, regimes, drift_spread,
# train_len) — changing any of these is a benchmark version bump
BENCHMARKS = {
    "SYN-A": {"seed": 2005, "T": 180, "vol": 0.008, "regimes": 2,
              "drift_spread": -0.015, "train_len": 150},
    "SYN-B": {"seed": 2006, "T": 180, "vol": 0.02, "regimes": 2,
              "drift_spread": 0.01, "train_len": 150},
}

REFERENCE_ROWS = {
    "LUV": {"mse": 0.0792, "mape_pct": 1.57, "r2": 0.8689},
    "RYA.L": {"mse": 1743.143, "mape_pct": 1.30, "r2": 0.9409},
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--warmup", type=int, default=300)
    ap.add_argument("--samples", type=int, default=300)
    ap.add_argument("--chains", type=int, default=1)
    ap.add_argument("--max-treedepth", type=int, default=6)
    ap.add_argument("--K", type=int, default=4)
    ap.add_argument("--L", type=int, default=3)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    import jax
    from hhmm_tpu.apps.hassan import simulate_ohlc, wf_forecast
    from hhmm_tpu.infer import SamplerConfig

    cfg = SamplerConfig(
        num_warmup=args.warmup,
        num_samples=args.samples,
        num_chains=args.chains,
        max_treedepth=args.max_treedepth,
    )
    out = {
        "reference": {
            "note": "real-quote replication impossible without network; "
            "rows from hassan2005/main.Rmd:920-933,1024-1037 for context",
            "rows": REFERENCE_ROWS,
            "config": "K=4, L=3, 800 iter, 1 chain (hassan2005/main.R:13-36)",
        },
        "config": {
            "K": args.K, "L": args.L, "warmup": args.warmup,
            "samples": args.samples, "chains": args.chains,
            "max_treedepth": args.max_treedepth,
        },
        "benchmarks": {},
    }
    for name, spec in BENCHMARKS.items():
        rng = np.random.default_rng(spec["seed"])
        ohlc = simulate_ohlc(
            rng, T=spec["T"], vol=spec["vol"], regimes=spec["regimes"],
            drift_spread=spec["drift_spread"],
        )
        res = wf_forecast(
            np.asarray(ohlc),
            train_len=spec["train_len"],
            K=args.K,
            L=args.L,
            config=cfg,
            key=jax.random.PRNGKey(spec["seed"]),
        )
        out["benchmarks"][name] = {
            "spec": spec,
            "n_steps": int(len(res.point)),
            "mse": float(res.errors["mse"]),
            "mape_pct": float(res.errors["mape"]),
            "r2": float(res.errors["r2"]),
            "divergence_rate": float(np.mean(res.diverged)),
        }
        print(name, json.dumps(out["benchmarks"][name]))

    os.makedirs(RESULTS, exist_ok=True)
    path = args.out or os.path.join(RESULTS, "hassan_replication.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", os.path.abspath(path))


if __name__ == "__main__":
    main()
