"""Tayal (2009) driver — the reference's `tayal2009/main.R`: ticks →
zig-zag features → lite-model fit with an out-of-sample day → top-state
labeling → per-regime analytics → trading vs buy-and-hold.

  python examples/tayal_main.py                    # simulated tick days
  python examples/tayal_main.py --ticks-dir DIR    # per-day CSVs (see
                                                   # hhmm_tpu.apps.data_io)
"""

from __future__ import annotations

import sys, os
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import configure, save_figure, standard_parser


def main() -> None:
    ap = standard_parser(__doc__)
    ap.add_argument("--ticks-dir", default=None)
    ap.add_argument("--symbol", default=None, help="file-name filter for --ticks-dir")
    ap.add_argument("--train-days", type=int, default=5)
    ap.add_argument("--legs-per-day", type=int, default=300, help="simulation size")
    ap.add_argument("--lag", type=int, default=1)
    args = ap.parse_args()
    cfg = configure(args)

    import jax

    from hhmm_tpu.apps.tayal.pipeline import run_window

    if args.ticks_dir:
        from hhmm_tpu.apps.data_io import load_tick_days

        days = load_tick_days(args.ticks_dir, symbol=args.symbol)
    else:
        from hhmm_tpu.apps.tayal.simulate import simulate_ticks

        rng = np.random.default_rng(args.seed)
        days = []
        for _ in range(args.train_days + 1):
            price, size, tsec, _ = simulate_ticks(rng, n_legs=args.legs_per_day)
            days.append({"price": price, "size": size, "t_seconds": tsec})
    if len(days) < args.train_days + 1:
        raise SystemExit(f"need {args.train_days + 1} days, have {len(days)}")
    days = days[: args.train_days + 1]

    price = np.concatenate([d["price"] for d in days])
    size = np.concatenate([d["size"] for d in days])
    tsec = np.concatenate([d["t_seconds"] for d in days])
    ins_end = sum(len(d["price"]) for d in days[: args.train_days]) - 1
    print(f"{len(days)} days, {len(price)} ticks, in-sample through tick {ins_end}")

    res = run_window(
        price, size, tsec, ins_end,
        config=cfg, key=jax.random.PRNGKey(args.seed), lags=(args.lag,),
    )
    div = float(np.asarray(res.stats["diverging"]).mean())
    print(f"divergence rate: {div:.4f}; "
          f"{res.n_ins_legs} in-sample legs, swapped={res.swapped}")
    print("per-regime summary over the full window (`topstate_summary`):")
    for label, stats in res.summary.items():
        row = ", ".join(f"{k}={v:.4g}" for k, v in stats.items())
        print(f"  {label}: {row}")
    tr = res.trades[args.lag]
    oos_price = price[ins_end + 1 :]
    print(f"out-of-sample trading (lag={args.lag}): {len(tr)} trades, "
          f"total {100 * np.sum(tr.ret):.3f}% vs buy&hold {100 * np.sum(res.bnh):.3f}%")

    if args.plots_dir:
        import matplotlib

        matplotlib.use("Agg")
        from hhmm_tpu.apps.tayal.features import expand_to_ticks
        from hhmm_tpu.viz.state_plots import plot_topstate_seq, plot_topstate_trading

        tick_top = expand_to_ticks(res.leg_topstate, res.zig, len(price))
        fig = plot_topstate_seq(oos_price, tick_top[ins_end + 1 :])
        save_figure(fig, args.plots_dir, "tayal_topstate_seq.png")
        fig = plot_topstate_trading(
            oos_price, tick_top[ins_end + 1 :], {f"lag {args.lag}": tr}
        )
        save_figure(fig, args.plots_dir, "tayal_trading.png")


if __name__ == "__main__":
    main()
